"""Fleet digital twin: a deterministic discrete-event goodput simulator
closed-loop-validated against the measured ledger.

PR 7's cost model prices a plan WITHOUT executing it; PR 10's goodput
ledger measures where wall-clock ACTUALLY went. This module connects
them: replay a supervisor policy (`train/supervisor.py SupervisorPolicy`
- the exact struct the real supervisor executes) over a synthetic
failure trace at 2..1000+ chips and emit a *predicted*, schema-compatible
goodput run record (`utils/goodput.py` taxonomy, capacity-seconds like
the fleet aggregation). Every robustness knob - checkpoint cadence,
restart budget, backoff, min-procs, grow hysteresis - becomes a search
problem for a fleet we don't own (ROADMAP item 5; failure-aware
efficiency as the first-class metric per arXiv 2204.06514, reshard and
restart costs as modeled quantities per arXiv 2112.01075).

**Inputs, in preference order:**

- *measured distributions* (`utils/goodput.py extract_distributions`,
  ``tools/goodput.py --distributions``): restart-gap / checkpoint-save /
  reshard / init / compile / steady-step durations sampled from real
  ``run_record.json`` events - the twin draws event durations from what
  this hardware actually does;
- *cost-model step times* (`analysis/cost.py step_seconds`): a roofline
  per-step seconds estimate from a plan's byte/flop terms, for plans and
  fleets never executed - which also gives autoshard its second scoring
  axis (`rank_plans_by_goodput`): plans ranked by goodput-under-failures
  instead of steady-state bytes alone;
- *policy fallbacks* (`SimPolicy` fields) when neither exists.

**Event model.** One elastic group, mirroring the supervisor's state
machine: generations run init -> compile -> (k steps + checkpoint)
cycles; a failure event loses the work since the last durable checkpoint
(a *preemption* event writes a cooperative emergency checkpoint first,
losing nothing), consumes one unit of the restart budget with the
policy's own exponential backoff, and restarts shrunk by one - or at the
same size when the event hits rank 0, the coordinator, taking the whole
group - charging the gap at the relaunched size plus the new
generation's init+compile into ``restart_gap`` (the fleet aggregation's
reclassification rule). Below ``min_procs`` or past ``max_restarts`` the
sim aborts exactly where the supervisor would. A shrunk group grows back
to target after ``grow_after_s`` healthy seconds (planned: emergency
checkpoints, no budget, no lost work). Conservation is ASSERTED like the
ledger's: the buckets must partition simulated capacity-seconds computed
independently from the generation windows.

**Closing the loop.** ``predict_from_ledger`` replays the ACTUAL failure
history recorded in a fleet record's generation list - measured
init/compile/exogenous stalls per rank, measured step time and
checkpoint cadence - and re-derives the bucket split from the event
model alone; `compare_records` asserts sim-vs-ledger bucket agreement
within tolerance (``tools/fleetsim.py --validate``, wired into the
2-proc chaos CI job so prediction drift fails the build). The optimal
checkpoint cadence from `cadence_search` is cross-checked against the
Young/Daly first-order optimum ``sqrt(2 * delta * MTBF)`` on synthetic
Poisson traces (tests/test_fleetsim.py).

Stdlib-only (no jax, no numpy): the twin runs in the supervisor, in CI,
and on a laptop; cost-model pricing imports `.cost` lazily. Determinism
is a contract: same seed + trace + policy -> bitwise-identical record
(`random.Random` over int seeds only; no wall-clock stamps).
Semantics: docs/OBSERVABILITY.md "Fleet digital twin".

**Serve mode (the second twin).** The back half of this module is the
SERVING fleet's digital twin: `simulate_serve` replays open-loop
arrivals (Poisson via `synthesize_arrivals`, or a recorded
``loadgen --arrival-trace`` stream) through the full per-request
lifecycle of `serve/scheduler.py` - admission, chunked prefill,
continuous-batching decode ticks, a modeled KV block pool with
OutOfBlocks parking and youngest-preempt + replay, spec-decode
acceptance as a sampled distribution, router dispatch and
`autoscale_decision` replayed over replica-failure traces - pricing
each tick from a checked-in servelint manifest via
`analysis.cost.serve_tick_seconds` (roofline), from measured
per-request records (`utils/goodput.py extract_serve_distributions`,
empirical), or from `ServePolicy` fallbacks. It emits a
schema-compatible ``kind:"sim"`` serve-taxonomy goodput record plus a
`/v1/requests`-shaped requests document (renderable by
``tools/goodput.py`` / ``tools/request_trace.py`` unchanged),
closed-loop-validated against measured serve-smoke runs
(`predict_serve_from_run`, ``tools/fleetsim.py --serve --validate``),
and answers the capacity question the static roofline can't:
`replicas_for_dynamic` searches replica count under QUEUEING until the
SLO holds, reported alongside `cost.replicas_for_target`'s static
floor. Semantics: docs/OBSERVABILITY.md "Serve digital twin".
"""

from __future__ import annotations

import dataclasses
import json
import math
import random
from collections import deque
from dataclasses import dataclass, field

from ..train.supervisor import SupervisorPolicy
from ..utils.goodput import (
    CAUSES,
    GOODPUT_CAUSE,
    IDLE_CAUSE,
    RECORD_VERSION,
    SERVE_CAUSES,
    SERVE_GOODPUT_CAUSE,
    extract_distributions,
    extract_serve_distributions,
    fleet_goodput_record,
    record_causes,
    validate_record,
)

_INF = float("inf")


# ---------------------------------------------------------- distributions


class Distributions:
    """Empirical event-duration distributions (the ``--distributions``
    document from `utils/goodput.py extract_distributions`). ``sample``
    draws uniformly from the quantile-preserving sample list -
    deterministic given the caller's seeded `random.Random` - and falls
    back to the recorded mean, then to the caller's default."""

    def __init__(self, doc: dict | None = None):
        doc = doc or {}
        if doc and doc.get("kind") not in (None, "distributions"):
            raise ValueError(
                f"not a distributions document (kind={doc.get('kind')!r}; "
                "produce one with tools/goodput.py --distributions)"
            )
        self.doc = doc
        self.causes = dict(doc.get("causes") or {})
        self.derived = dict(doc.get("derived") or {})

    @classmethod
    def from_records(cls, records) -> "Distributions":
        return cls(extract_distributions(records))

    @classmethod
    def load(cls, path: str) -> "Distributions":
        with open(path) as f:
            return cls(json.load(f))

    def has(self, cause: str) -> bool:
        return cause in self.causes

    def mean(self, cause: str, default: float = 0.0) -> float:
        info = self.causes.get(cause)
        if not info:
            return float(default)
        return float(info.get("mean_s") or default)

    def sample(self, cause: str, rng: random.Random,
               default: float = 0.0) -> float:
        info = self.causes.get(cause)
        if not info:
            return float(default)
        xs = info.get("samples_s")
        if xs:
            return float(xs[rng.randrange(len(xs))])
        return float(info.get("mean_s") or default)

    def step_overhead_s(self, default: float = 0.0) -> float:
        return float(self.derived.get("step_overhead_s") or default)


# -------------------------------------------------------- failure traces


@dataclass(frozen=True)
class FailureEvent:
    """One machine-level event on the failure trace. ``rank`` is taken
    modulo the CURRENT group size at fire time (a chip that fails still
    fails whoever runs on it after a shrink); rank 0 is the coordinator
    - its death takes the whole group (same-size restart), matching the
    supervisor's coordinator-death semantics. ``kind`` is ``failure``
    (work since the last checkpoint is lost) or ``preemption`` (a
    SIGTERM-style eviction: the cooperative emergency checkpoint lands
    first, so no work is lost - but the restart budget is still spent,
    exactly like a PREEMPT_RC worker exit)."""

    t_s: float
    rank: int
    kind: str = "failure"


def synthesize_failure_trace(
    n_chips: int,
    *,
    rate_per_chip_per_h: float,
    horizon_s: float,
    seed: int = 0,
    preempt_fraction: float = 0.0,
) -> list:
    """A seeded Poisson failure trace: exponential inter-arrivals at the
    aggregate rate ``n_chips * rate_per_chip_per_h`` with uniform victim
    ranks. Deterministic: same arguments -> identical trace (int-seeded
    `random.Random`; never the wall clock)."""
    if n_chips < 1:
        raise ValueError(f"n_chips must be >= 1, got {n_chips}")
    rate_s = n_chips * float(rate_per_chip_per_h) / 3600.0
    if rate_s <= 0:
        return []
    rng = random.Random(int(seed) * 2654435761 % (2**31) + 17)
    events = []
    t = 0.0
    while True:
        t += rng.expovariate(rate_s)
        if t >= horizon_s:
            return events
        kind = (
            "preemption" if rng.random() < preempt_fraction else "failure"
        )
        events.append(FailureEvent(round(t, 6), rng.randrange(n_chips), kind))


# --------------------------------------------------------------- policy


@dataclass
class SimPolicy:
    """One simulated configuration: the shared `SupervisorPolicy` (the
    struct the real supervisor runs) plus the workload knobs the
    supervisor does not own - checkpoint cadence and step pricing - and
    fallback durations used only where no empirical distribution sample
    exists."""

    supervisor: SupervisorPolicy
    checkpoint_every_steps: int = 0  # 0 = never checkpoint
    step_time_s: float = 1.0
    step_overhead_s: float = 0.0  # host time between steps (idle_other)
    tokens_per_step: float = 0.0
    # fallback durations (overridden by Distributions samples)
    init_s: float = 5.0
    compile_s: float = 10.0
    checkpoint_write_s: float = 1.0
    restart_gap_s: float = 10.0
    label: str = ""

    def __post_init__(self):
        if self.checkpoint_every_steps < 0:
            raise ValueError("checkpoint_every_steps must be >= 0")
        if self.step_time_s <= 0:
            raise ValueError("step_time_s must be > 0")

    def with_(self, **changes) -> "SimPolicy":
        """A copy with knobs changed; `SupervisorPolicy` field names
        route into the nested policy, so one sweep spec can mix both
        levels (``with_(checkpoint_every_steps=200, max_restarts=8)``)."""
        sup_fields = {f.name for f in dataclasses.fields(SupervisorPolicy)}
        sup_changes = {k: v for k, v in changes.items() if k in sup_fields}
        own = {k: v for k, v in changes.items() if k not in sup_fields}
        sup = (
            dataclasses.replace(self.supervisor, **sup_changes)
            if sup_changes else self.supervisor
        )
        return dataclasses.replace(self, supervisor=sup, **own)

    def describe(self) -> dict:
        doc = {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(SimPolicy)
            if f.name != "supervisor"
        }
        doc["supervisor"] = self.supervisor.policy_dict()
        return doc


def policy_variants(base: SimPolicy, sweep: dict) -> list:
    """The cartesian product of ``{knob: [values...]}`` over a base
    policy, each labeled with its deviating knobs - the grid
    `rank_policies` (and ``tools/fleetsim.py --sweep``) ranks."""
    variants = [base]
    for knob, values in sweep.items():
        variants = [
            v.with_(**{knob: val}) for v in variants for val in values
        ]
    for v in variants:
        if not v.label:
            v.label = ",".join(
                f"{k}={_fmt_knob(v, k)}" for k in sweep
            ) or "base"
    return variants


def _fmt_knob(policy: SimPolicy, knob: str):
    sup_fields = {f.name for f in dataclasses.fields(SupervisorPolicy)}
    src = policy.supervisor if knob in sup_fields else policy
    v = getattr(src, knob)
    return f"{v:g}" if isinstance(v, float) else v


# ------------------------------------------------------------- simulator


class _Sim:
    """One simulation run's state; `simulate()` is the public face."""

    def __init__(self, policy, trace, dists, horizon_s, target_steps, seed):
        self.p = policy
        self.sup = policy.supervisor
        self.dists = dists or Distributions()
        self.rng = random.Random((int(seed) * 1000003 + 1) % (2**31))
        self.horizon = float(horizon_s)
        self.target = target_steps
        self.events = sorted(trace, key=lambda e: (e.t_s, e.rank))
        self.ei = 0
        self.t = 0.0
        self.n = self.sup.nprocs
        self.gen = -1
        self.buckets = {c: 0.0 for c in CAUSES}
        self.wall_check = 0.0
        self.steps_executed = 0
        self.steps_done = 0  # unique frontier (reverts on lost work)
        self.last_ckpt = 0
        self.tokens = 0.0
        self.lost_steps = 0
        self.lost_capacity_s = 0.0
        self.restarts_used = 0
        self.failures_seen = 0
        self.preemptions_seen = 0
        self.grows = 0
        self.events_in_gaps = 0
        self.gaps = []
        self.aborted = None
        self.restart_reason = None

    # -------------------------------------------------------- primitives

    def charge(self, cause: str, dur: float) -> None:
        if dur > 0:
            self.buckets[cause] += dur * self.n

    def next_event_t(self) -> float:
        return self.events[self.ei].t_s if self.ei < len(self.events) else _INF

    def run_segment(self, cause: str, dur: float) -> str:
        """Advance through one non-step segment; a failure event or the
        horizon may interrupt it (the elapsed part is still charged)."""
        end = self.t + max(dur, 0.0)
        stop = min(self.next_event_t(), self.horizon)
        if end <= stop:
            self.charge(cause, end - self.t)
            self.t = end
            return "ok"
        self.charge(cause, max(stop - self.t, 0.0))
        self.t = stop
        return "horizon" if stop >= self.horizon else "failure"

    def charge_steps(self, m: int) -> None:
        self.charge(GOODPUT_CAUSE, m * self.p.step_time_s)
        self.charge(IDLE_CAUSE, m * self.p.step_overhead_s)
        self.steps_executed += m
        self.steps_done += m
        self.tokens += m * self.p.tokens_per_step

    def emergency_checkpoint(self) -> str:
        """Cooperative save before a planned stop / preemption exit: the
        unique-step frontier becomes durable."""
        ck = self.dists.sample(
            "checkpoint_save", self.rng, self.p.checkpoint_write_s
        )
        st = self.run_segment("checkpoint_save", ck)
        if st != "failure":
            self.last_ckpt = self.steps_done
        return st

    # -------------------------------------------------------- generation

    def run_gen(self):
        """One generation, start to teardown. Returns (status, event):
        status in done|horizon|failure|grow; on failure the event is
        consumed and lost work / the preemption checkpoint is already
        accounted - the restart DECISION belongs to the outer loop."""
        self.gen += 1
        gen_t0 = self.t
        n0 = self.n
        # events that fired while no worker existed hit nobody
        while self.ei < len(self.events) and self.events[self.ei].t_s <= self.t:
            self.ei += 1
            self.events_in_gaps += 1
        # a failure-relaunched generation's init+compile is restart cost
        # (the fleet aggregation's reclassification rule)
        startup_cause = (
            "restart_gap" if self.restart_reason == "failure" else None
        )
        st = self.run_segment(
            startup_cause or "init",
            self.dists.sample("init", self.rng, self.p.init_s),
        )
        if st == "ok":
            st = self.run_segment(
                startup_cause or "compile",
                self.dists.sample("compile", self.rng, self.p.compile_s),
            )
        healthy_t = self.t
        since_ckpt = 0
        k = self.p.checkpoint_every_steps
        cyc = self.p.step_time_s + self.p.step_overhead_s
        grow_t = (
            healthy_t + self.sup.grow_after_s
            if self.sup.grow_after_s > 0 and self.n < self.sup.nprocs
            else _INF
        )
        while st == "ok":
            if self.target is not None and self.steps_done >= self.target:
                st = "done"
                break
            if self.t >= grow_t:
                st = "grow"
                break
            rem = (
                self.target - self.steps_done
                if self.target is not None else None
            )
            r = k - since_ckpt if k > 0 else (rem if rem is not None else 4096)
            if rem is not None:
                r = min(r, rem)
            r = max(int(r), 1)
            stop = min(self.next_event_t(), self.horizon, grow_t)
            if self.t + r * cyc <= stop:
                self.charge_steps(r)
                self.t += r * cyc
                since_ckpt += r
                if k > 0 and since_ckpt >= k and not (
                    self.target is not None and self.steps_done >= self.target
                ):
                    st = self.run_segment(
                        "checkpoint_save",
                        self.dists.sample(
                            "checkpoint_save", self.rng,
                            self.p.checkpoint_write_s,
                        ),
                    )
                    if st == "ok":
                        self.last_ckpt = self.steps_done
                        since_ckpt = 0
                continue
            # an event/horizon/grow boundary lands inside the block
            avail = max(stop - self.t, 0.0)
            full = min(int(avail // cyc), r)
            if full > 0:
                self.charge_steps(full)
                since_ckpt += full
            part = avail - full * cyc
            if part > 0:
                # the interrupted step's partial wall was real compute;
                # it completed no step, so no progress is counted
                self.charge(GOODPUT_CAUSE, part)
            self.t = stop
            if stop >= self.horizon:
                st = "horizon"
            elif stop >= grow_t and stop < self.next_event_t():
                st = "grow"
            else:
                st = "failure"
        ev = None
        if st == "failure":
            ev = self.events[self.ei]
            self.ei += 1
            if ev.kind == "preemption":
                self.preemptions_seen += 1
                sub = self.emergency_checkpoint()
                if sub == "horizon":
                    st = "horizon"
            else:
                self.failures_seen += 1
                lost = self.steps_done - self.last_ckpt
                if lost > 0:
                    self.lost_steps += lost
                    self.lost_capacity_s += lost * self.p.step_time_s * n0
                    self.steps_done = self.last_ckpt
        elif st == "grow":
            sub = self.emergency_checkpoint()
            if sub == "horizon":
                st = "horizon"
            elif sub == "failure":
                st = "failure-during-grow"
        self.wall_check += (self.t - gen_t0) * n0
        return st, ev

    # -------------------------------------------------------------- run

    def run(self) -> dict:
        while True:
            st, ev = self.run_gen()
            if st in ("done", "horizon"):
                break
            if st == "grow":
                self.grows += 1
                # teardown -> respawn with no worker alive: the ledger
                # never measures this window for PLANNED restarts (no
                # restart_gaps entry), so no capacity is charged
                self.t += self.dists.sample(
                    "restart_gap", self.rng, self.p.restart_gap_s
                )
                self.n = self.sup.nprocs
                self.restart_reason = "grow"
                continue
            if st == "failure-during-grow":
                # the grow teardown collided with a failure event: the
                # emergency checkpoint did not land, so work since the
                # last durable one is lost - then the failure path runs
                ev = self.events[self.ei]
                self.ei += 1
                self.failures_seen += 1
                lost = self.steps_done - self.last_ckpt
                if lost > 0:
                    self.lost_steps += lost
                    self.lost_capacity_s += (
                        lost * self.p.step_time_s * self.n
                    )
                    self.steps_done = self.last_ckpt
            # ---- the supervisor's restart decision
            self.restarts_used += 1
            if self.restarts_used > self.sup.max_restarts:
                self.aborted = (
                    f"restart budget ({self.sup.max_restarts}) exhausted"
                )
                break
            whole_group = ev is not None and (ev.rank % self.n) == 0
            new_n = self.n if whole_group else self.n - 1
            if new_n < self.sup.min_procs:
                self.aborted = (
                    f"only {new_n} worker(s) survive but min_procs is "
                    f"{self.sup.min_procs}"
                )
                break
            pause = self.sup.backoff_for(self.restarts_used)
            gap = pause + self.dists.sample(
                "restart_gap", self.rng, self.p.restart_gap_s
            )
            gap = min(gap, max(self.horizon - self.t, 0.0))
            self.n = new_n
            self.charge("restart_gap", gap)
            self.wall_check += gap * new_n
            self.gaps.append({
                "seconds": round(gap, 6), "group_size": new_n,
                "generation": self.gen + 1, "backoff_s": round(pause, 6),
            })
            self.t += gap
            self.restart_reason = "failure"
            if self.t >= self.horizon:
                break
        return self.record()

    def record(self) -> dict:
        buckets = self.buckets
        wall = sum(buckets.values())
        if any(v < 0 for v in buckets.values()) or (
            abs(wall - self.wall_check) > max(1e-6 * max(wall, 1.0), 1e-9)
        ):
            raise AssertionError(
                "fleetsim conservation violated: buckets sum to "
                f"{wall:.9f} capacity-seconds but the generation windows "
                f"cover {self.wall_check:.9f} "
                f"({json.dumps({k: round(v, 6) for k, v in buckets.items()})})"
                " - a segment was charged twice or skipped; this is a "
                "simulator bug, please report it"
            )
        goodput = buckets[GOODPUT_CAUSE]
        effective = max(goodput - self.lost_capacity_s, 0.0)
        return {
            "version": RECORD_VERSION,
            "kind": "sim",
            "final": True,
            "steps": self.steps_executed,
            "goodput_steps": self.steps_executed,
            "tokens": round(self.tokens, 6),
            "wall_s": round(wall, 6),
            "goodput_s": round(goodput, 6),
            "goodput_ratio": round(goodput / wall, 6) if wall > 0 else None,
            "badput_s": {
                c: round(buckets[c], 6) for c in CAUSES
                if c != GOODPUT_CAUSE
            },
            "restart_gaps": self.gaps,
            "metrics": {
                "unique_steps": self.steps_done,
                "lost_steps": self.lost_steps,
                "lost_step_capacity_s": round(self.lost_capacity_s, 6),
                "effective_goodput_ratio": round(effective / wall, 6)
                if wall > 0 else None,
                "aborted": self.aborted is not None,
                "abort_reason": self.aborted,
                "restarts_used": self.restarts_used,
                "generations": self.gen + 1,
                "failures_seen": self.failures_seen,
                "preemptions_seen": self.preemptions_seen,
                "grows": self.grows,
                "events_in_gaps": self.events_in_gaps,
                "final_group_size": self.n,
                "horizon_s": self.horizon,
            },
        }


def simulate(
    policy: SimPolicy,
    trace,
    dists: Distributions | None = None,
    *,
    horizon_s: float,
    target_steps: int | None = None,
    seed: int = 0,
) -> dict:
    """Run one policy over one failure trace and return the predicted
    schema-compatible run record (``kind: "sim"``; renderable, diffable,
    and gateable by ``tools/goodput.py`` like any measured record).

    ``goodput_ratio`` mirrors the LEDGER's definition (every executed
    steady step counts, replays included - what a measured record would
    report); ``metrics.effective_goodput_ratio`` additionally subtracts
    the capacity-seconds of steps whose progress a later failure erased
    - the quantity policy search actually maximizes. Deterministic:
    same (policy, trace, seed) -> bitwise-identical record."""
    sim = _Sim(policy, trace, dists, horizon_s, target_steps, seed)
    rec = sim.run()
    rec["sim"] = {
        "mode": "forward",
        "seed": int(seed),
        "n_events": len(sim.events),
        "policy": policy.describe(),
    }
    return rec


# ------------------------------------------------------- policy ranking


def effective_ratio(rec: dict) -> float:
    v = (rec.get("metrics") or {}).get("effective_goodput_ratio")
    if v is None:
        v = rec.get("goodput_ratio")
    return float(v or 0.0)


def rank_policies(
    policies,
    dists: Distributions | None = None,
    *,
    n_chips: int,
    rate_per_chip_per_h: float,
    horizon_s: float,
    preempt_fraction: float = 0.0,
    seeds=(0, 1, 2),
) -> list:
    """Simulate every policy over the SAME seeded traces (common random
    numbers - policy deltas are not drowned by trace noise) and rank by
    mean effective goodput ratio, aborting policies last. Returns
    ``[{label, policy, effective_goodput_ratio, goodput_ratio, aborted,
    record}, ...]`` best first; ``record`` is the first seed's."""
    traces = [
        synthesize_failure_trace(
            n_chips, rate_per_chip_per_h=rate_per_chip_per_h,
            horizon_s=horizon_s, seed=s,
            preempt_fraction=preempt_fraction,
        )
        for s in seeds
    ]
    out = []
    for policy in policies:
        recs = [
            simulate(policy, tr, dists, horizon_s=horizon_s, seed=s)
            for s, tr in zip(seeds, traces)
        ]
        aborted = any(r["metrics"]["aborted"] for r in recs)
        out.append({
            "label": policy.label or "policy",
            "policy": policy.describe(),
            "effective_goodput_ratio": round(
                sum(effective_ratio(r) for r in recs) / len(recs), 6
            ),
            "goodput_ratio": round(
                sum(float(r.get("goodput_ratio") or 0.0) for r in recs)
                / len(recs), 6
            ),
            "aborted": aborted,
            "record": recs[0],
        })
    out.sort(key=lambda d: (d["aborted"], -d["effective_goodput_ratio"]))
    return out


# ------------------------------------------------------- cadence search


def young_daly_interval(mtbf_s: float, checkpoint_s: float) -> float:
    """The Young/Daly first-order optimal checkpoint interval
    ``sqrt(2 * delta * M)`` (seconds of work between checkpoints) for
    checkpoint cost ``delta`` and group MTBF ``M``."""
    return math.sqrt(2.0 * float(checkpoint_s) * float(mtbf_s))


def cadence_search(
    policy: SimPolicy,
    dists: Distributions | None = None,
    *,
    rate_per_chip_per_h: float,
    horizon_s: float,
    cadences=None,
    seeds=(0, 1),
    grid_ratio: float = 1.15,
) -> dict:
    """Derive the optimal checkpoint cadence for a policy by simulation,
    cross-checked against the Young/Daly approximation.

    The knob is isolated from elasticity: every synthesized event is
    remapped to rank 0 (whole-group, same-size restarts - the classic
    single-domain model Young/Daly assumes) and the restart budget is
    lifted. The default cadence grid is geometric between the checkpoint
    cost and the group MTBF (the a-priori bracket of the optimum).
    Returns ``{"results", "best", "young_daly"}`` where ``results`` is
    ``[(cadence_steps, interval_s, mean_effective_ratio), ...]``."""
    sup = dataclasses.replace(
        policy.supervisor, max_restarts=10**9, grow_after_s=0.0
    )
    base = dataclasses.replace(policy, supervisor=sup)
    n = sup.nprocs
    mtbf_s = 3600.0 / (n * rate_per_chip_per_h)
    delta = (dists or Distributions()).mean(
        "checkpoint_save", policy.checkpoint_write_s
    )
    cyc = policy.step_time_s + policy.step_overhead_s
    if cadences is None:
        cadences = []
        tau = max(delta, cyc)
        while tau <= mtbf_s:
            k = max(int(round(tau / cyc)), 1)
            if not cadences or k != cadences[-1]:
                cadences.append(k)
            tau *= grid_ratio
    traces = [
        [
            FailureEvent(e.t_s, 0, e.kind)
            for e in synthesize_failure_trace(
                n, rate_per_chip_per_h=rate_per_chip_per_h,
                horizon_s=horizon_s, seed=s,
            )
        ]
        for s in seeds
    ]
    results = []
    for k in cadences:
        cand = base.with_(checkpoint_every_steps=int(k))
        ratios = [
            effective_ratio(
                simulate(cand, tr, dists, horizon_s=horizon_s, seed=s)
            )
            for s, tr in zip(seeds, traces)
        ]
        results.append((
            int(k), round(k * cyc, 6),
            round(sum(ratios) / len(ratios), 6),
        ))
    best = max(results, key=lambda r: r[2]) if results else None
    yd_s = young_daly_interval(mtbf_s, delta)
    return {
        "results": results,
        "best": best,
        "young_daly": {
            "interval_s": round(yd_s, 6),
            "cadence_steps": max(int(round(yd_s / cyc)), 1),
            "mtbf_s": round(mtbf_s, 6),
            "checkpoint_s": round(delta, 6),
            "ratio_vs_best": round(best[1] / yd_s, 6)
            if best and yd_s > 0 else None,
        },
    }


# --------------------------------------------- closing the loop (validate)


def _fill_window(avail_s: float, step_s: float, overhead_s: float,
                 k: int, ck_mean_s: float):
    """The shared cadence arithmetic: how many steps + periodic
    checkpoints fit in ``avail_s`` seconds at ``step_s`` + per-step host
    ``overhead_s``, checkpointing every ``k`` steps at ``ck_mean_s``.
    Returns ``(steps, steady_s, checkpoint_s, idle_s)`` partitioning
    ``avail_s`` exactly."""
    if avail_s <= 0 or step_s <= 0:
        return 0, 0.0, 0.0, max(avail_s, 0.0)
    cyc = step_s + overhead_s
    if k > 0 and ck_mean_s > 0:
        block = k * cyc + ck_mean_s
        full = int(avail_s // block)
        rem = avail_s - full * block
        steps = full * k + min(int(rem // cyc), k)
        ckpts = full
    else:
        steps = int(avail_s // cyc)
        ckpts = 0
    steady = steps * step_s
    ck = ckpts * ck_mean_s
    return steps, steady, ck, max(avail_s - steady - ck, 0.0)


# badput causes the sim cannot predict from policy alone (injected chaos,
# input pipeline, elastic resharding, guard replays): replayed as
# exogenous inputs in validation so conservation closes
EXOGENOUS_CAUSES = ("stall", "data_wait", "reshard", "rollback_recompute")


def _predict_rank(rec: dict) -> dict:
    """Re-derive one rank record's bucket split from the event model +
    the record's own measured inputs (wall window, init/compile, mean
    step time, checkpoint cadence, exogenous chaos): the closed-loop
    consistency check - if the sim's cycle arithmetic or taxonomy
    semantics drift from the ledger's, the prediction diverges."""
    bad = dict(rec.get("badput_s") or {})
    events = rec.get("events") or {}
    wall = float(rec.get("wall_s") or 0.0)
    steps = int(rec.get("steps") or 0)
    gsteps = int(rec.get("goodput_steps") or 0)
    steady_ev = events.get("steady_step") or {}
    step_s = float(steady_ev.get("mean_s") or 0.0)
    if step_s <= 0 and gsteps > 0:
        step_s = float(rec.get("goodput_s") or 0.0) / gsteps
    init_s = float(bad.get("init") or 0.0)
    compile_s = float(bad.get("compile") or 0.0)
    exo = {c: float(bad.get(c) or 0.0) for c in EXOGENOUS_CAUSES}
    ck_ev = events.get("checkpoint_save") or {}
    ck_mean = float(ck_ev.get("mean_s") or 0.0)
    cfg = rec.get("config") or {}
    try:
        k = int(cfg.get("checkpoint_every") or 0)
    except (TypeError, ValueError):
        k = 0
    overhead = (
        float(bad.get(IDLE_CAUSE) or 0.0) / steps if steps > 0 else 0.0
    )
    avail = max(wall - init_s - compile_s - sum(exo.values()), 0.0)
    if ck_mean > 0 and k <= 0:
        # saves observed but no cadence recorded (non-lm CLI): price the
        # measured saves directly and fill the rest with steps
        ck_total = float(ck_ev.get("total_s") or 0.0)
        avail = max(avail - ck_total, 0.0)
        steps_pred, steady_s, _, idle_s = _fill_window(
            avail, step_s, overhead, 0, 0.0
        )
        ckpt_s = ck_total
    else:
        steps_pred, steady_s, ckpt_s, idle_s = _fill_window(
            avail, step_s, overhead, k, ck_mean
        )
    badput = {c: 0.0 for c in CAUSES if c != GOODPUT_CAUSE}
    badput.update({
        "init": round(init_s, 6),
        "compile": round(compile_s, 6),
        "checkpoint_save": round(ckpt_s, 6),
        IDLE_CAUSE: round(idle_s, 6),
    })
    badput.update({c: round(v, 6) for c, v in exo.items()})
    return {
        "version": RECORD_VERSION,
        "kind": "rank",
        "final": rec.get("final"),
        "rank": rec.get("rank"),
        "generation": rec.get("generation"),
        "steps": steps_pred,
        "goodput_steps": steps_pred,
        "tokens": 0.0,
        "wall_s": round(wall, 6),
        "goodput_s": round(steady_s, 6),
        "goodput_ratio": round(steady_s / wall, 6) if wall > 0 else None,
        "badput_s": badput,
    }


def predict_from_ledger(fleet_record: dict, rank_records) -> dict:
    """Replay the ACTUAL failure history a fleet record captured - its
    generation list, per-rank windows, and measured restart gaps -
    through the sim's event model, returning the predicted fleet record
    (``kind: "sim"``). Agreement with the measured record (within
    `compare_records` tolerances) is the closed-loop validation the CI
    chaos job gates on."""
    fleet = validate_record(fleet_record, "fleet record")
    gaps = list(fleet.get("restart_gaps") or ())
    restart_gens = {
        int(g["generation"]) for g in gaps
        if isinstance(g.get("generation"), int)
    }
    preds = [_predict_rank(validate_record(r)) for r in rank_records]
    if not preds:
        raise ValueError(
            "no rank records to replay (need the run dir's "
            "records/gen{g}_rank{r}.json write-through records)"
        )
    pred = fleet_goodput_record(
        preds, restart_gaps=gaps, restart_generations=restart_gens
    )
    pred["kind"] = "sim"
    pred["sim"] = {"mode": "validate", "n_rank_records": len(preds)}
    return pred


def compare_records(
    predicted: dict, measured: dict, *,
    ratio_tol: float = 0.1, share_tol: float = 0.1,
) -> list:
    """Bucket-level agreement check: |predicted - measured| of
    ``goodput_ratio`` within ``ratio_tol`` and of every cause's
    wall-clock SHARE within ``share_tol`` (absolute, both directions -
    the sim must neither flatter nor slander a bucket). Returns
    violation strings, empty = agree."""
    problems = []
    rp = predicted.get("goodput_ratio")
    rm = measured.get("goodput_ratio")
    if rp is None or rm is None:
        problems.append(
            "goodput_ratio missing from "
            + ("the prediction" if rp is None else "the measured record")
        )
    elif abs(rp - rm) > ratio_tol:
        problems.append(
            f"goodput_ratio: predicted {rp:.4f} vs measured {rm:.4f} "
            f"(|diff| {abs(rp - rm):.4f} > tol {ratio_tol:.3f})"
        )
    cp, cm = record_causes(predicted), record_causes(measured)
    tp = float(predicted.get("wall_s") or 0.0)
    tm = float(measured.get("wall_s") or 0.0)
    for c in sorted(set(list(cp) + list(cm))):
        sp = cp.get(c, 0.0) / tp if tp > 0 else 0.0
        sm = cm.get(c, 0.0) / tm if tm > 0 else 0.0
        if abs(sp - sm) > share_tol:
            problems.append(
                f"bucket '{c}': predicted share {sp:.2%} vs measured "
                f"{sm:.2%} (|diff| {abs(sp - sm):.2%} > tol "
                f"{share_tol:.2%})"
            )
    return problems


# --------------------------------------- autoshard's second scoring axis


def rank_plans_by_goodput(
    plan_docs,
    policy: SimPolicy,
    dists: Distributions | None = None,
    *,
    hw=None,
    flops_per_step: float = 0.0,
    rate_per_chip_per_h: float,
    horizon_s: float,
    seeds=(0, 1),
) -> list:
    """Rank autoshard plan manifests (``analysis/plans/*.json`` docs) by
    predicted goodput-under-failures instead of steady-state bytes: each
    plan's ``chosen`` byte terms are priced into per-step seconds by
    `analysis.cost.step_seconds` (the only lazy non-stdlib hop), then
    every plan is simulated over the SAME seeded failure traces under
    ``policy``.

    The ranking metric is **surviving progress per capacity-second**
    (``progress_steps_per_cap_s``: unique steps whose work no failure
    erased, over fleet capacity-seconds) - NOT the time-fraction
    ``goodput_ratio``, which cannot tell plans apart (a faster step does
    not earn a larger SHARE of wall-clock, it earns more steps per
    second; with a step-cadenced checkpoint policy a slower plan can
    even post a higher time-fraction by checkpointing less often per
    hour while making far less progress). Comparable across plans that
    share the global batch. Returns ``[{plan, config, step_s, step_why,
    progress_steps_per_cap_s, effective_goodput_ratio, goodput_ratio,
    score}, ...]`` best first."""
    from .cost import step_seconds

    candidates = []
    for doc in plan_docs:
        chosen = doc.get("chosen") if isinstance(doc, dict) else None
        if not chosen:
            raise ValueError(
                "not an autoshard plan manifest (no 'chosen' block); "
                "generate one with tools/autoshard.py --write-manifest"
            )
        st = step_seconds(chosen, hw, flops_per_step=flops_per_step)
        cand = policy.with_(step_time_s=max(st.step_s, 1e-9))
        cand.label = str(chosen.get("plan") or doc.get("config") or "plan")
        candidates.append((doc, chosen, st, cand))
    traces = [
        synthesize_failure_trace(
            policy.supervisor.nprocs,
            rate_per_chip_per_h=rate_per_chip_per_h,
            horizon_s=horizon_s, seed=s,
        )
        for s in seeds
    ]
    out = []
    for doc, chosen, st, cand in candidates:
        recs = [
            simulate(cand, tr, dists, horizon_s=horizon_s, seed=s)
            for s, tr in zip(seeds, traces)
        ]
        progress = [
            r["metrics"]["unique_steps"] / r["wall_s"]
            if r["wall_s"] > 0 else 0.0
            for r in recs
        ]
        out.append({
            "plan": cand.label,
            "config": doc.get("config"),
            "step_s": round(st.step_s, 9),
            "step_why": st.why(),
            "progress_steps_per_cap_s": round(
                sum(progress) / len(progress), 9
            ),
            "effective_goodput_ratio": round(
                sum(effective_ratio(r) for r in recs) / len(recs), 6
            ),
            "goodput_ratio": round(
                sum(float(r.get("goodput_ratio") or 0.0) for r in recs)
                / len(recs), 6
            ),
            "aborted": any(r["metrics"]["aborted"] for r in recs),
            "score": chosen.get("score"),
        })
    out.sort(
        key=lambda d: (d["aborted"], -d["progress_steps_per_cap_s"])
    )
    return out


# ======================================================== serve-mode twin
#
# Everything below simulates the SERVING fleet (serve/scheduler.py +
# serve/fleet.py) instead of the training supervisor. Stdlib-only like
# the rest of the module: serve/* imports jax transitively, so the two
# pieces of serve arithmetic the twin shares with the runtime - the
# TTFT/E2E percentile decomposition and the autoscaler policy - exist
# here as local mirrors, each pinned equal to the real implementation
# by tests/test_fleetsim_serve.py (the mirror drifts -> the test fails).

#: Per-request span causes (mirror of serve/reqtrace.py REQUEST_CAUSES).
SERVE_REQUEST_CAUSES = (
    "queue_wait",
    "admission",
    "prefill",
    "decode",
    "kv_alloc_stall",
    "preempted_wait",
    "stream_write",
)


def _req_tolerance(total: float) -> float:
    return max(1e-6 * max(total, 1.0), 1e-9)


def _serve_percentile(xs, q: float):
    """Nearest-rank percentile (stdlib mirror of reqtrace.percentile)."""
    if not xs:
        return None
    s = sorted(xs)
    return s[max(0, math.ceil(q * len(s)) - 1)]


def _serve_clipped_causes(rec: dict, metric: str) -> dict:
    """Per-cause seconds of one request detail clipped at the metric's
    endpoint (stdlib mirror of reqtrace.clipped_causes)."""
    if metric == "ttft":
        clip = rec.get("t_first_token_rel")
        if clip is None:
            return {}
        clip = float(clip)
    else:
        clip = _INF
    out: dict = {}
    for cause, t0, t1 in rec.get("spans") or ():
        lo, up = float(t0), min(float(t1), clip)
        if up > lo:
            out[cause] = out.get(cause, 0.0) + (up - lo)
    return out


def _serve_decompose(records, metric: str, q: float):
    """TTFT/E2E percentile + per-cause share decomposition of the tail
    (stdlib mirror of reqtrace.decompose - the exact arithmetic
    serve/fleet.py slo_readout judges the real fleet with)."""
    key = "ttft_s" if metric == "ttft" else "e2e_s"
    vals = [
        (r, float(r[key])) for r in records
        if isinstance(r, dict) and r.get(key) is not None
    ]
    if not vals:
        return None
    pv = _serve_percentile([v for _, v in vals], q)
    tail = [r for r, v in vals if v >= pv - 1e-12]
    acc: dict = {}
    for r in tail:
        for cause, s in _serve_clipped_causes(r, metric).items():
            acc[cause] = acc.get(cause, 0.0) + s
    total = sum(acc.values())
    shares = {
        c: (v / total if total > 0 else 0.0)
        for c, v in sorted(acc.items())
    }
    dominant = (
        max(shares.items(), key=lambda kv: kv[1])[0] if shares else None
    )
    return {"value": pv, "shares": shares, "dominant": dominant}


def _autoscale_fallback(
    *, actual, min_replicas, max_replicas, queue_depth=0, queue_high=8,
    gates=None, idle_s=0.0, scale_down_idle_s=60.0,
) -> dict:
    """Stdlib mirror of serve/fleet.py `autoscale_decision` (pinned
    equal by test); used when the real one (jax-transitive import)
    isn't loadable."""
    violated = {
        k: g for k, g in (gates or {}).items() if g.get("violated")
    }
    queue_dom = [
        k for k, g in violated.items()
        if g.get("dominant") == "queue_wait"
    ]
    kv_dom = [
        k for k, g in violated.items()
        if g.get("dominant") == "kv_alloc_stall"
    ]
    if queue_dom:
        if actual < max_replicas:
            return {
                "action": "scale_up", "target": actual + 1,
                "reason": "queue_wait-dominant SLO violation "
                f"({', '.join(sorted(queue_dom))})",
            }
        return {
            "action": "hold", "target": actual,
            "reason": "queue_wait-dominant SLO violation but already "
            f"at max_replicas={max_replicas}",
        }
    if kv_dom:
        return {
            "action": "hold", "target": actual,
            "reason": "kv_alloc_stall-dominant SLO violation "
            f"({', '.join(sorted(kv_dom))}): add KV capacity "
            "(--num-blocks / int8-kv), replicas won't help",
        }
    if queue_depth >= queue_high:
        if actual < max_replicas:
            return {
                "action": "scale_up", "target": actual + 1,
                "reason": f"queue depth {queue_depth} >= {queue_high}",
            }
        return {
            "action": "hold", "target": actual,
            "reason": f"queue depth {queue_depth} but already at "
            f"max_replicas={max_replicas}",
        }
    if idle_s >= scale_down_idle_s and actual > min_replicas:
        return {
            "action": "scale_down", "target": actual - 1,
            "reason": f"idle {idle_s:.0f}s >= {scale_down_idle_s:.0f}s",
        }
    return {"action": "hold", "target": actual, "reason": "steady"}


def _autoscale(**kw) -> dict:
    try:
        from ..serve.fleet import autoscale_decision
    except Exception:
        return _autoscale_fallback(**kw)
    return autoscale_decision(**kw)


# ----------------------------------------------------------- arrivals


def synthesize_arrivals(
    rate_rps: float, *,
    n_requests: int | None = None,
    horizon_s: float | None = None,
    prompt_lens=(4, 8, 16),
    max_new: int = 16,
    poisson: bool = True,
    seed: int = 0,
    dists: "Distributions | None" = None,
) -> list:
    """Seeded open-loop arrival stream: ``[{t_s, prompt_len,
    max_new_tokens}, ...]`` sorted by time. Mirrors tools/loadgen.py
    pacing (first request at t=0, then exponential or fixed gaps) so a
    sim replay and a measured run can share one arrival process. When
    ``dists`` carries serve pools (`extract_serve_distributions`),
    prompt/output lengths are sampled from the measured workload mix
    instead of the cycled defaults."""
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if n_requests is None and horizon_s is None:
        raise ValueError("need n_requests or horizon_s")
    rng = random.Random(int(seed) * 2654435761 % (2 ** 31) + 29)
    out = []
    t = 0.0
    i = 0
    while True:
        if n_requests is not None and i >= n_requests:
            break
        if horizon_s is not None and t > horizon_s:
            break
        if dists is not None and dists.has("prompt_len"):
            plen = max(1, int(round(dists.sample("prompt_len", rng, 4))))
        else:
            plen = int(prompt_lens[i % len(prompt_lens)])
        if dists is not None and dists.has("output_len"):
            mnew = max(1, int(round(dists.sample("output_len", rng, max_new))))
        else:
            mnew = int(max_new)
        out.append({
            "t_s": round(t, 9),
            "prompt_len": plen,
            "max_new_tokens": mnew,
        })
        i += 1
        t += rng.expovariate(rate_rps) if poisson else 1.0 / rate_rps
    return out


def load_arrivals(doc) -> list:
    """Normalize an arrival-trace document (``loadgen --arrival-trace``
    output ``{"arrivals": [...]}`` or a bare list) into the
    `synthesize_arrivals` shape, sorted by time."""
    rows = doc.get("arrivals") if isinstance(doc, dict) else doc
    if not isinstance(rows, list):
        raise ValueError(
            "not an arrival trace (expected {'arrivals': [...]} or a "
            "list; produce one with tools/loadgen.py --arrival-trace)"
        )
    out = []
    for r in rows:
        out.append({
            "t_s": float(r.get("t_s") or 0.0),
            "prompt_len": max(1, int(r.get("prompt_len") or 1)),
            "max_new_tokens": max(1, int(r.get("max_new_tokens") or 1)),
        })
    out.sort(key=lambda a: a["t_s"])
    return out


# ---------------------------------------------------------- ServePolicy


@dataclass
class ServePolicy:
    """Everything the serve twin needs to know about one fleet: engine
    geometry (mirrors serve/scheduler.py SchedulerConfig), fleet/router
    shape, autoscaler knobs (mirrors serve/fleet.py autoscale_decision),
    SLO gates, and service-time fallbacks used when neither measured
    distributions nor a servelint manifest price a tick."""

    # engine geometry (SchedulerConfig mirror)
    max_batch: int = 4
    block_size: int = 4
    usable_blocks: int = 8
    max_seq_len: int = 32
    prefill_chunk: int = 4
    spec_decode: int = 0
    block_headroom: int = 0
    max_queue: int = 64
    idle_poll_s: float = 0.02
    # fleet shape
    replicas: int = 1
    min_replicas: int = 1
    max_replicas: int = 0          # 0 -> replicas (autoscaling off ceiling)
    autoscale_every_s: float = 0.0  # 0 -> autoscaler off
    queue_high: int = 8
    scale_down_idle_s: float = 60.0
    provision_s: float = 10.0      # scale-up decision -> replica live
    restart_gap_s: float = 10.0    # failure -> replacement live
    slo: dict = field(default_factory=dict)  # e.g. {"ttft_p99": 0.5}
    # service-time fallbacks (used only without dists/manifest pricing)
    decode_tick_s: float = 1e-3
    prefill_token_s: float = 1e-4
    stream_write_s: float = 0.0
    spec_accept_rate: float = 0.6
    label: str = ""

    def with_(self, **changes) -> "ServePolicy":
        return dataclasses.replace(self, **changes)

    def describe(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("label", None)
        return d

    @classmethod
    def from_manifest(cls, manifest: dict, **over) -> "ServePolicy":
        """Geometry from a checked-in servelint manifest
        (``analysis/serve/*.json``: ``engine`` + ``kv`` blocks)."""
        eng = dict(manifest.get("engine") or {})
        kv = dict(manifest.get("kv") or {})
        kw = dict(
            max_batch=int(eng.get("max_batch") or 4),
            block_size=int(eng.get("block_size") or 4),
            usable_blocks=int(
                kv.get("usable_blocks")
                or max(int(eng.get("num_blocks") or 2) - 1, 1)
            ),
            max_seq_len=int(eng.get("max_seq_len") or 32),
            prefill_chunk=int(eng.get("prefill_chunk") or 4),
            spec_decode=int(eng.get("spec_decode") or 0),
        )
        kw.update(over)
        return cls(**kw)

    @classmethod
    def from_record(cls, rec: dict, **over) -> "ServePolicy":
        """Geometry from a measured serve run record's embedded config
        (``config.engine`` / ``config.scheduler`` blocks)."""
        cfg = dict(rec.get("config") or {})
        eng = dict(cfg.get("engine") or {})
        sched = dict(cfg.get("scheduler") or {})
        kw = dict(
            max_batch=int(eng.get("max_batch") or 4),
            block_size=int(eng.get("block_size") or 4),
            usable_blocks=max(int(eng.get("num_blocks") or 2) - 1, 1),
            max_seq_len=int(eng.get("max_seq_len") or 32),
            prefill_chunk=int(eng.get("prefill_chunk") or 4),
            spec_decode=int(eng.get("spec_decode") or 0),
            max_queue=int(sched.get("max_queue") or 64),
        )
        kw.update(over)
        return cls(**kw)


# ----------------------------------------------------------- ServePricer


class ServePricer:
    """Prices one engine call (decode tick at batch B / width W, prefill
    chunk of N tokens) from the best available source, in preference
    order mirroring the training twin's:

    - **empirical**: measured per-request pools
      (`extract_serve_distributions`: ``decode_tick_s`` /
      ``prefill_token_s`` / ``acceptance_rate``) - validate mode;
    - **roofline**: a checked-in servelint manifest's bucket grid priced
      by `analysis.cost.serve_tick_seconds` (lazy import, the planning
      mode that needs no runtime) - lookup snaps to the smallest bucket
      >= the requested (B, W), clamped to the grid maximum;
    - **fallback**: `ServePolicy` constants.
    """

    def __init__(self, policy: "ServePolicy",
                 dists: "Distributions | None" = None,
                 manifest: dict | None = None,
                 hw="cpu-host"):
        self.policy = policy
        self.dists = dists
        self._decode_grid: dict = {}
        self._prefill_grid: dict = {}
        if dists is not None and dists.has("decode_tick_s"):
            self.mode = "empirical"
        elif manifest and manifest.get("buckets"):
            self.mode = "roofline"
            from .cost import HARDWARE_MODELS, serve_tick_seconds
            model = (
                HARDWARE_MODELS[hw] if isinstance(hw, str) else hw
            )
            for b in manifest["buckets"]:
                fam = b.get("family")
                key = tuple(int(x) for x in b.get("bucket") or ())
                if fam not in ("decode", "prefill") or len(key) != 2:
                    continue
                tick = serve_tick_seconds(b, model).step_s
                grid = (
                    self._decode_grid if fam == "decode"
                    else self._prefill_grid
                )
                grid[key] = tick
            if not self._decode_grid:
                self.mode = "fallback"
        else:
            self.mode = "fallback"

    @staticmethod
    def _grid_lookup(grid: dict, b: int, w: int) -> float:
        """Smallest bucket >= (b, w) in both axes - the scheduler's
        bucket-membership rule - clamped to the grid max."""
        fits = [k for k in grid if k[0] >= b and k[1] >= w]
        if fits:
            key = min(fits)
        else:
            key = max(grid)
        return grid[key]

    def decode_tick(self, batch: int, width: int,
                    rng: random.Random) -> float:
        if self.mode == "empirical":
            return max(
                self.dists.sample(
                    "decode_tick_s", rng, self.policy.decode_tick_s
                ), 1e-9,
            )
        if self.mode == "roofline":
            return max(
                self._grid_lookup(self._decode_grid, batch, width), 1e-9
            )
        return max(self.policy.decode_tick_s, 1e-9)

    def prefill_call(self, tokens: int, width: int,
                     rng: random.Random) -> float:
        if tokens <= 0:
            return 0.0
        if self.mode == "empirical":
            per = max(
                self.dists.sample(
                    "prefill_token_s", rng, self.policy.prefill_token_s
                ), 1e-12,
            )
            return per * tokens
        if self.mode == "roofline" and self._prefill_grid:
            return max(
                self._grid_lookup(self._prefill_grid, tokens, width), 1e-9
            )
        return max(self.policy.prefill_token_s * tokens, 1e-9)

    def acceptance(self, k: int, rng: random.Random) -> int:
        """Accepted draft tokens out of ``k`` proposed: prefix-truncated
        sampling (accept while an independent coin lands under the
        acceptance rate - the spec-decode verifier's actual rule)."""
        if k <= 0:
            return 0
        if self.dists is not None and self.dists.has("acceptance_rate"):
            rate = min(max(self.dists.sample(
                "acceptance_rate", rng, self.policy.spec_accept_rate
            ), 0.0), 1.0)
        else:
            rate = min(max(self.policy.spec_accept_rate, 0.0), 1.0)
        n = 0
        while n < k and rng.random() < rate:
            n += 1
        return n


# ------------------------------------------------- sim request / replica


class _SimRequest:
    __slots__ = (
        "req_id", "arrival", "prompt_len", "max_new", "state", "emitted",
        "prefill_done", "prefill_target", "tokens_held", "blocks",
        "spans", "t_admit", "t_wait0", "t_first_token", "t_done",
        "preemptions", "router_retries", "decode_ticks", "prefill_tokens",
        "replayed_ticks", "engine_s", "proposed", "accepted", "episodes",
    )

    def __init__(self, req_id: str, arrival: float, prompt_len: int,
                 max_new: int):
        self.req_id = req_id
        self.arrival = arrival
        self.prompt_len = prompt_len
        self.max_new = max_new
        self.state = "queued"
        self.emitted = 0
        self.prefill_done = 0
        self.prefill_target = prompt_len
        self.tokens_held = 0
        self.blocks = 0
        self.spans = []          # [cause, t0_abs, t1_abs], merged
        self.t_admit = None
        self.t_wait0 = arrival
        self.t_first_token = None
        self.t_done = None
        self.preemptions = 0
        self.router_retries = 0
        self.decode_ticks = 0
        self.prefill_tokens = 0
        self.replayed_ticks = 0
        self.engine_s = {}
        self.proposed = 0
        self.accepted = 0
        self.episodes = 1

    def span(self, cause: str, t0: float, t1: float):
        if t1 <= t0:
            return
        if self.spans and self.spans[-1][0] == cause \
                and abs(self.spans[-1][2] - t0) < 1e-12:
            self.spans[-1][2] = t1
        else:
            self.spans.append([cause, t0, t1])

    def charge_engine(self, cause: str, s: float):
        if s > 0:
            self.engine_s[cause] = self.engine_s.get(cause, 0.0) + s

    def detail(self, origin: float) -> dict:
        """`serve/reqtrace.py detail()`-shaped dict, times relative to
        ``origin`` (the sim's t=0)."""
        causes = {}
        for c, t0, t1 in self.spans:
            causes[c] = round(causes.get(c, 0.0) + (t1 - t0), 9)
        dominant = (
            max(causes.items(), key=lambda kv: kv[1])[0] if causes else None
        )
        ttft = (
            self.t_first_token - self.arrival
            if self.t_first_token is not None else None
        )
        e2e = (
            self.t_done - self.arrival if self.t_done is not None else None
        )
        out = {
            "req_id": self.req_id,
            "tenant": "sim",
            "state": self.state,
            "tokens_emitted": self.emitted,
            "preemptions": self.preemptions,
            "dominant_cause": dominant,
            "ttft_s": round(ttft, 9) if ttft is not None else None,
            "e2e_s": round(e2e, 9) if e2e is not None else None,
            "prompt_len": self.prompt_len,
            "max_new_tokens": self.max_new,
            "decode_ticks": self.decode_ticks,
            "prefill_tokens": self.prefill_tokens,
            "replayed_ticks": self.replayed_ticks,
            "t_first_token_rel": (
                round(self.t_first_token - origin, 9)
                if self.t_first_token is not None else None
            ),
            "spans": [
                [c, round(t0 - origin, 9), round(t1 - origin, 9)]
                for c, t0, t1 in self.spans
            ],
            "causes": causes,
            "engine_s": {
                c: round(v, 9) for c, v in sorted(self.engine_s.items())
            },
            "episodes": self.episodes,
        }
        if self.proposed:
            out["proposed_tokens"] = self.proposed
            out["accepted_tokens"] = self.accepted
            out["acceptance_rate"] = round(
                self.accepted / self.proposed, 6
            )
        if self.router_retries:
            out["router_retries"] = self.router_retries
        return out


class _Replica:
    __slots__ = (
        "idx", "queue", "preempted", "active", "free_blocks", "buckets",
        "t_up", "up_s", "busy_until", "idle_since", "wait_since", "alive",
        "pending_kill", "plan", "tick_t0", "tokens", "ticks",
        "event_samples",
    )

    def __init__(self, idx: int, t: float, usable_blocks: int):
        self.idx = idx
        self.queue = deque()
        self.preempted = deque()
        self.active = []
        self.free_blocks = usable_blocks
        self.buckets = {c: 0.0 for c in SERVE_CAUSES}
        self.event_samples = {c: [] for c in SERVE_CAUSES}
        self.t_up = t
        self.up_s = 0.0
        self.busy_until = None    # None -> idle
        self.idle_since = t
        self.wait_since = None    # earliest unserved work while idle
        self.alive = True
        self.pending_kill = False
        self.plan = None
        self.tick_t0 = None
        self.tokens = 0
        self.ticks = 0

    def load(self) -> int:
        return len(self.queue) + len(self.active) + len(self.preempted)

    def charge(self, cause: str, s: float):
        if s > 0:
            self.buckets[cause] = self.buckets.get(cause, 0.0) + s
            self.event_samples.setdefault(cause, []).append(s)


# ------------------------------------------------------- serve event loop


class _ServeSim:
    """Discrete-event serving-fleet simulator. Time advances to the next
    of: arrival, tick completion, idle-poll quantized tick start, replica
    failure, replica spawn, autoscale timer - events at equal times are
    processed in one fixed order (spawns, failures, arrivals, tick
    completions, tick starts, autoscale), so the record is bitwise
    deterministic for the same policy + arrivals + trace + seed."""

    def __init__(self, policy: ServePolicy, arrivals: list,
                 pricer: ServePricer, failure_trace=(), seed: int = 0):
        self.policy = policy
        self.pricer = pricer
        self.rng = random.Random((int(seed) * 1000003 + 7) % (2 ** 31))
        self.arrivals = sorted(
            arrivals, key=lambda a: (a["t_s"], a.get("prompt_len", 0))
        )
        self.failures = sorted(failure_trace, key=lambda e: e.t_s)
        self.replicas: list = []
        self.retired: list = []
        self.pending_spawns: list = []   # spawn-live times
        self.limbo: deque = deque()      # requests with no live replica
        self.finalized: list = []
        self.rejected = 0
        self.rejected_too_long = 0
        self.preemptions = 0
        self.router_retries = 0
        self.autoscale_log: list = []
        self.fleet_idle_since = None
        self._ai = 0                      # next arrival index
        self._fi = 0                      # next failure index
        self._next_req = 0
        self._next_idx = 0
        self.autoscale_next = (
            policy.autoscale_every_s if policy.autoscale_every_s > 0
            else None
        )
        for _ in range(max(policy.replicas, 1)):
            self._spawn(0.0)

    # ---- helpers

    def _spawn(self, t: float) -> "_Replica":
        rep = _Replica(self._next_idx, t, self.policy.usable_blocks)
        self._next_idx += 1
        self.replicas.append(rep)
        return rep

    def _live(self) -> list:
        return [r for r in self.replicas if r.alive]

    def blocks_for(self, tokens: int) -> int:
        return max(
            (int(tokens) + self.policy.block_size - 1)
            // self.policy.block_size, 0,
        )

    def _max_replicas(self) -> int:
        return self.policy.max_replicas or max(self.policy.replicas, 1)

    # ---- router

    def dispatch(self, s: _SimRequest, t: float):
        """Least-loaded live-replica dispatch (serve/fleet.py
        FleetRouter's policy); limbo when no replica is live."""
        p = self.policy
        if s.prompt_len + s.max_new > p.max_seq_len or (
            self.blocks_for(s.prompt_len + s.max_new + 1)
            + p.block_headroom > p.usable_blocks
        ):
            self.rejected_too_long += 1
            s.state = "rejected"
            return
        live = self._live()
        if not live:
            self.limbo.append(s)
            return
        rep = min(live, key=lambda r: (r.load(), r.idx))
        if len(rep.queue) >= p.max_queue:
            self.rejected += 1
            s.state = "rejected"
            return
        rep.queue.append(s)
        self.fleet_idle_since = None
        if rep.busy_until is None and rep.wait_since is None:
            rep.wait_since = t

    # ---- tick start

    def _charge_idle(self, rep: _Replica, t0: float):
        """Charge the idle window [idle_since, t0]: the part during
        which a request was already waiting goes to queue_wait (the
        ledger sweep's priority rule - queue_wait claims otherwise-idle
        seconds), the rest to idle_other."""
        span = t0 - rep.idle_since
        if span <= 0:
            return
        qw = 0.0
        if rep.wait_since is not None:
            qw = min(max(t0 - max(rep.wait_since, rep.idle_since), 0.0),
                     span)
        if qw > 0:
            rep.charge("queue_wait", qw)
        if span - qw > 0:
            rep.charge(IDLE_CAUSE, span - qw)
        rep.idle_since = t0
        rep.wait_since = None

    def start_tick(self, rep: _Replica, t0: float):
        p = self.policy
        if rep.busy_until is None:
            self._charge_idle(rep, t0)
        # re-admit preempted first (the scheduler's rule), FIFO
        while rep.preempted and len(rep.active) < p.max_batch:
            s = rep.preempted[0]
            if self.blocks_for(s.prompt_len + s.emitted + 1) \
                    > rep.free_blocks:
                break
            rep.preempted.popleft()
            s.span("preempted_wait", s.t_wait0, t0)
            s.state = "active"
            s.prefill_target = s.prompt_len + s.emitted
            s.prefill_done = 0
            rep.active.append(s)
        # admit new work
        while rep.queue and len(rep.active) < p.max_batch:
            s = rep.queue[0]
            if self.blocks_for(s.prompt_len + 1) + p.block_headroom \
                    > rep.free_blocks:
                break
            rep.queue.popleft()
            s.span("queue_wait", s.t_wait0, t0)
            if s.t_admit is None:
                s.t_admit = t0
            s.state = "active"
            rep.active.append(s)
        if not rep.active:
            # nothing admissible yet: one idle-poll stall quantum; the
            # waiting request keeps accumulating queue_wait
            rep.plan = None
            rep.tick_t0 = t0
            rep.busy_until = t0 + p.idle_poll_s
            rep.charge("queue_wait" if (rep.queue or rep.preempted)
                       else IDLE_CAUSE, p.idle_poll_s)
            return
        # plan actions oldest-first; youngest-preempt on OutOfBlocks
        order = sorted(rep.active, key=lambda s: (s.arrival, s.req_id))
        planned: dict = {}
        prefills: list = []
        decoders: list = []
        for s in order:
            if s.state != "active" or id(s) in planned:
                continue
            if s.prefill_done < s.prefill_target:
                n = min(p.prefill_chunk, s.prefill_target - s.prefill_done)
                kind = "prefill"
                proposed = accepted = 0
            else:
                k = max(p.spec_decode, 0)
                accepted = self.pricer.acceptance(k, self.rng) if k else 0
                n = min(1 + accepted, s.max_new - s.emitted)
                proposed = k
                kind = "decode"
            new_held = s.tokens_held + n
            nb = self.blocks_for(new_held + 1)
            for _attempt in (0, 1):
                need = nb - s.blocks
                if need <= rep.free_blocks:
                    break
                victims = [
                    v for v in rep.active
                    if v is not s and id(v) not in planned and v.blocks > 0
                    and v.state == "active"
                ]
                if not victims:
                    break
                victim = max(victims, key=lambda v: (v.arrival, v.req_id))
                rep.free_blocks += victim.blocks
                victim.blocks = 0
                victim.tokens_held = 0
                victim.prefill_done = 0
                victim.preemptions += 1
                victim.episodes += 1
                victim.state = "preempted"
                victim.t_wait0 = t0
                self.preemptions += 1
                rep.active.remove(victim)
                rep.preempted.append(victim)
            need = nb - s.blocks
            if need > rep.free_blocks:
                continue                  # parked this tick
            rep.free_blocks -= need
            s.blocks = nb
            planned[id(s)] = True
            if kind == "prefill":
                prefills.append((s, n))
            else:
                decoders.append((s, n, proposed, accepted))
        parked = [
            s for s in rep.active
            if s.state == "active" and id(s) not in planned
        ]
        if not prefills and not decoders:
            # every admitted sequence is OutOfBlocks-parked
            d = p.idle_poll_s
            rep.charge("kv_alloc_stall", d)
            for s in parked:
                s.span("kv_alloc_stall", t0, t0 + d)
                s.charge_engine("kv_alloc_stall", d / len(parked))
            rep.plan = {"prefills": [], "decoders": [], "stall": True}
            rep.tick_t0 = t0
            rep.busy_until = t0 + d
            return
        width = max(
            [s.blocks for s, *_ in prefills]
            + [s.blocks for s, *_ in decoders] + [1]
        )
        prefill_time = 0.0
        pf = []
        for s, n in prefills:
            c = self.pricer.prefill_call(n, s.blocks, self.rng)
            prefill_time += c
            pf.append((s, n, c))
        decode_time = (
            self.pricer.decode_tick(len(decoders), width, self.rng)
            if decoders else 0.0
        )
        d = prefill_time + decode_time
        t1 = t0 + d
        if prefill_time > 0:
            rep.charge("prefill", prefill_time)
        if decode_time > 0:
            rep.charge("decode", decode_time)
        for s, n, c in pf:
            s.span("prefill", t0, t1)
            s.charge_engine("prefill", c)
        total_emit = sum(n for _, n, _, _ in decoders) or 1
        for s, n, _, _ in decoders:
            s.span("decode", t0, t1)
            s.charge_engine("decode", decode_time * n / total_emit)
        for s in parked:
            s.span("kv_alloc_stall", t0, t1)
            s.charge_engine("kv_alloc_stall", 0.0)
        rep.plan = {
            "prefills": pf, "decoders": decoders, "stall": False,
        }
        rep.tick_t0 = t0
        rep.busy_until = t1

    # ---- tick completion

    def complete_tick(self, rep: _Replica, t1: float):
        p = self.policy
        plan = rep.plan
        rep.plan = None
        if plan is not None and not plan.get("stall"):
            for s, n, c in plan["prefills"]:
                s.prefill_done += n
                s.tokens_held += n
                s.prefill_tokens += n
                if s.episodes > 1:
                    s.replayed_ticks += 1
            for s, n, proposed, accepted in plan["decoders"]:
                s.emitted += n
                s.tokens_held += n
                s.decode_ticks += 1
                s.proposed += proposed
                s.accepted += accepted
                rep.tokens += n
                if s.t_first_token is None and n > 0:
                    s.t_first_token = t1
                if s.emitted >= s.max_new:
                    rep.free_blocks += s.blocks
                    s.blocks = 0
                    s.state = "done"
                    s.t_done = t1 + p.stream_write_s
                    if p.stream_write_s > 0:
                        s.span("stream_write", t1, s.t_done)
                        s.charge_engine("stream_write", p.stream_write_s)
                    rep.active.remove(s)
                    spanned = sum(u1 - u0 for _, u0, u1 in s.spans)
                    total = s.t_done - s.arrival
                    assert abs(spanned - total) <= _req_tolerance(total), (
                        f"request {s.req_id}: spans {spanned:.9f}s != "
                        f"lifetime {total:.9f}s"
                    )
                    self.finalized.append(s)
            rep.ticks += 1
        if rep.pending_kill:
            self._kill(rep, t1)
            return
        if rep.active or rep.preempted or rep.queue:
            self.start_tick(rep, t1)
        else:
            rep.busy_until = None
            rep.idle_since = t1
            rep.wait_since = None

    # ---- failure / retirement

    def _kill(self, rep: _Replica, t: float):
        """Replica death: in-flight and queued requests lose their KV
        state and bounce back through the router (replay on
        re-admission), mirroring the PR 18 failover path."""
        if rep.busy_until is None:
            self._charge_idle(rep, t)
        rep.alive = False
        rep.pending_kill = False
        rep.busy_until = None
        rep.up_s += t - rep.t_up
        self.retired.append(rep)
        self.replicas.remove(rep)
        displaced = []
        for s in rep.active:
            s.blocks = 0
            s.tokens_held = 0
            s.prefill_done = 0
            s.episodes += 1
            s.state = "queued"
            s.t_wait0 = t
            displaced.append(s)
        for s in rep.preempted:
            s.span("preempted_wait", s.t_wait0, t)
            s.state = "queued"
            s.t_wait0 = t
            displaced.append(s)
        displaced.extend(rep.queue)
        rep.active = []
        rep.preempted.clear()
        rep.queue.clear()
        for s in displaced:
            s.router_retries += 1
            self.router_retries += 1
            self.dispatch(s, t)

    def _retire_idle(self, t: float) -> bool:
        idle = [
            r for r in self._live()
            if r.busy_until is None and not r.load()
        ]
        if not idle:
            return False
        rep = max(idle, key=lambda r: r.idx)
        self._charge_idle(rep, t)
        rep.alive = False
        rep.up_s += t - rep.t_up
        self.retired.append(rep)
        self.replicas.remove(rep)
        return True

    # ---- autoscaler replay

    def _gates(self) -> dict:
        gates = {}
        window = self.finalized[-64:]
        details = [s.detail(0.0) for s in window]
        for key, limit in sorted((self.policy.slo or {}).items()):
            metric, _, qs = key.partition("_p")
            if metric not in ("ttft", "e2e") or not qs:
                continue
            d = _serve_decompose(details, metric, float(qs) / 100.0)
            if d is None:
                continue
            gates[key] = {
                "value": d["value"],
                "limit": float(limit),
                "violated": d["value"] > float(limit),
                "dominant": d["dominant"],
                "shares": d["shares"],
            }
        return gates

    def _autoscale_step(self, t: float):
        p = self.policy
        live = self._live()
        actual = len(live) + len(self.pending_spawns)
        queue_depth = sum(len(r.queue) for r in live) + len(self.limbo)
        all_idle = live and all(
            r.busy_until is None and not r.load() for r in live
        ) and not self.limbo
        if all_idle:
            if self.fleet_idle_since is None:
                self.fleet_idle_since = t
        else:
            self.fleet_idle_since = None
        idle_s = (
            t - self.fleet_idle_since
            if self.fleet_idle_since is not None else 0.0
        )
        decision = _autoscale(
            actual=actual,
            min_replicas=p.min_replicas,
            max_replicas=self._max_replicas(),
            queue_depth=queue_depth,
            queue_high=p.queue_high,
            gates=self._gates(),
            idle_s=idle_s,
            scale_down_idle_s=p.scale_down_idle_s,
        )
        if decision["action"] == "scale_up":
            self.pending_spawns.append(t + p.provision_s)
        elif decision["action"] == "scale_down":
            if not self._retire_idle(t):
                decision = dict(
                    decision, action="hold",
                    reason=decision["reason"] + " (no idle replica)",
                )
        if decision["action"] != "hold" or decision["reason"] != "steady":
            self.autoscale_log.append({
                "t_s": round(t, 9),
                "replicas": len(self._live()),
                **decision,
            })

    # ---- main loop

    def run(self, horizon_s: float | None = None):
        p = self.policy
        guard = 0
        while True:
            guard += 1
            assert guard < 10_000_000, "serve sim failed to converge"
            cands = []
            if self._ai < len(self.arrivals):
                cands.append(self.arrivals[self._ai]["t_s"])
            for rep in self._live():
                if rep.busy_until is not None:
                    cands.append(rep.busy_until)
                elif rep.load():
                    # the real scheduler wakes on arrival (no poll
                    # latency on the first admission)
                    cands.append(max(
                        rep.idle_since,
                        rep.wait_since if rep.wait_since is not None
                        else rep.idle_since,
                    ))
            if self._fi < len(self.failures) and (
                self._ai < len(self.arrivals)
                or any(r.busy_until is not None or r.load()
                       for r in self._live())
                or self.limbo
            ):
                cands.append(self.failures[self._fi].t_s)
            if self.pending_spawns and (self.limbo or (
                self._ai < len(self.arrivals)
                or any(r.load() for r in self._live())
            )):
                cands.append(min(self.pending_spawns))
            if self.autoscale_next is not None and (
                self._ai < len(self.arrivals)
                or any(r.busy_until is not None or r.load()
                       for r in self._live())
                or self.limbo or self.pending_spawns
            ):
                cands.append(self.autoscale_next)
            if not cands:
                break
            t = min(cands)
            if horizon_s is not None and t > horizon_s \
                    and self._ai >= len(self.arrivals) \
                    and not any(r.busy_until is not None or r.load()
                                for r in self._live()) \
                    and not self.limbo:
                break
            # fixed processing order at time t
            spawned = [x for x in self.pending_spawns if x <= t + 1e-12]
            if spawned:
                self.pending_spawns = [
                    x for x in self.pending_spawns if x > t + 1e-12
                ]
                for _ in spawned:
                    self._spawn(t)
                while self.limbo:
                    self.dispatch(self.limbo.popleft(), t)
            while self._fi < len(self.failures) \
                    and self.failures[self._fi].t_s <= t + 1e-12:
                e = self.failures[self._fi]
                self._fi += 1
                live = self._live()
                if not live:
                    continue
                victim = sorted(live, key=lambda r: r.idx)[
                    e.rank % len(live)
                ]
                if victim.busy_until is None:
                    self._kill(victim, t)
                else:
                    victim.pending_kill = True
                self.pending_spawns.append(t + p.restart_gap_s)
            while self._ai < len(self.arrivals) \
                    and self.arrivals[self._ai]["t_s"] <= t + 1e-12:
                a = self.arrivals[self._ai]
                self._ai += 1
                s = _SimRequest(
                    f"sim-{self._next_req:06d}", a["t_s"],
                    a["prompt_len"], a["max_new_tokens"],
                )
                self._next_req += 1
                self.dispatch(s, t)
            for rep in sorted(self._live(), key=lambda r: r.idx):
                if rep.busy_until is not None \
                        and rep.busy_until <= t + 1e-12:
                    self.complete_tick(rep, rep.busy_until)
            for rep in sorted(self._live(), key=lambda r: r.idx):
                if rep.busy_until is None and rep.load():
                    start = max(
                        rep.idle_since,
                        rep.wait_since if rep.wait_since is not None
                        else rep.idle_since,
                    )
                    if start <= t + 1e-12:
                        self.start_tick(rep, start)
            if self.autoscale_next is not None \
                    and self.autoscale_next <= t + 1e-12:
                self._autoscale_step(self.autoscale_next)
                self.autoscale_next += p.autoscale_every_s
        # close out
        t_end = 0.0
        for rep in self.retired:
            t_end = max(t_end, rep.t_up + rep.up_s)
        for s in self.finalized:
            t_end = max(t_end, s.t_done)
        for rep in self._live():
            t_end = max(t_end, rep.idle_since, rep.t_up)
        if horizon_s is not None:
            t_end = max(t_end, 0.0)
        self.t_end = t_end
        for rep in self._live():
            self._charge_idle(rep, t_end)
            rep.up_s += t_end - rep.t_up


# ------------------------------------------------------- serve simulate


def _serve_pcts(details: list) -> dict:
    out = {}
    for metric in ("ttft", "e2e"):
        per = {}
        for q in (0.50, 0.95, 0.99):
            d = _serve_decompose(details, metric, q)
            if d is not None:
                per[f"p{int(q * 100)}"] = {
                    "value": round(d["value"], 9),
                    "shares": {
                        c: round(v, 6) for c, v in d["shares"].items()
                    },
                    "dominant": d["dominant"],
                }
        out[metric] = per
    return out


def simulate_serve(
    policy: ServePolicy,
    arrivals: list, *,
    dists: Distributions | None = None,
    manifest: dict | None = None,
    hw="cpu-host",
    failure_trace=(),
    horizon_s: float | None = None,
    seed: int = 0,
    wall_s: float | None = None,
):
    """Run the serving-fleet twin over one arrival stream. Returns
    ``(record, requests_doc)``:

    - ``record``: schema-compatible ``kind:"sim"`` serve-taxonomy
      goodput record (renderable by ``tools/goodput.py``, gateable by
      `compare_records` against a measured serve ledger) with predicted
      TTFT/E2E percentile decompositions under ``predicted``;
    - ``requests_doc``: a ``GET /v1/requests?full=1``-shaped document
      (``recent`` = finalized `serve/reqtrace.py detail()` dicts) that
      ``tools/request_trace.py`` renders unchanged.

    ``wall_s`` stretches the simulated wall to a measured run's (extra
    time charged to ``idle_other``) so validate-mode share comparisons
    align on the same denominator. Conservation is ASSERTED per replica,
    per finalized request, and in aggregate."""
    from ..utils.goodput import _dist_summary

    pricer = ServePricer(policy, dists, manifest, hw)
    sim = _ServeSim(policy, arrivals, pricer, failure_trace, seed)
    sim.run(horizon_s)
    everyone = sim.retired + sim.replicas
    buckets = {c: 0.0 for c in SERVE_CAUSES}
    pooled: dict = {c: [] for c in SERVE_CAUSES}
    wall = 0.0
    ticks = 0
    tokens = 0
    for rep in everyone:
        total = sum(rep.buckets.values())
        assert abs(total - rep.up_s) <= _req_tolerance(rep.up_s), (
            f"replica {rep.idx}: buckets {total:.9f}s != "
            f"up {rep.up_s:.9f}s"
        )
        for c, v in rep.buckets.items():
            buckets[c] = buckets.get(c, 0.0) + v
        for c, xs in rep.event_samples.items():
            pooled.setdefault(c, []).extend(xs)
        wall += rep.up_s
        ticks += rep.ticks
        tokens += rep.tokens
    if wall_s is not None and wall_s > wall:
        buckets[IDLE_CAUSE] += wall_s - wall
        wall = wall_s
    goodput = buckets.get(SERVE_GOODPUT_CAUSE, 0.0)
    badput = {
        c: round(v, 9) for c, v in buckets.items()
        if c != SERVE_GOODPUT_CAUSE
    }
    attributed = goodput + sum(badput.values())
    assert abs(attributed - wall) <= _req_tolerance(wall), (
        f"serve sim conservation: {attributed:.9f}s != {wall:.9f}s"
    )
    details = [s.detail(0.0) for s in sim.finalized]
    in_flight = sum(r.load() for r in sim.replicas) + len(sim.limbo)
    slo = policy.slo or {}
    attained = 0
    for s in sim.finalized:
        ok = True
        for key, limit in slo.items():
            metric, _, _q = key.partition("_p")
            v = (
                (s.t_first_token - s.arrival) if metric == "ttft"
                else (s.t_done - s.arrival)
            )
            if v is None or v > float(limit):
                ok = False
                break
        attained += 1 if ok else 0
    offered = len(sim.arrivals)
    record = {
        "version": RECORD_VERSION,
        "kind": "sim",
        "taxonomy": "serve",
        "final": True,
        "replicas": max(policy.replicas, 1),
        "replicas_launched": len(everyone),
        "steps": ticks,
        "goodput_steps": ticks,
        "tokens": tokens,
        "wall_s": round(wall, 9),
        "goodput_s": round(goodput, 9),
        "goodput_ratio": round(goodput / wall, 6) if wall > 0 else 0.0,
        "badput_s": badput,
        "events": {
            c: _dist_summary(xs) for c, xs in sorted(pooled.items()) if xs
        },
        "requests": {
            "offered": offered,
            "completed": len(sim.finalized),
            "rejected": sim.rejected,
            "rejected_too_long": sim.rejected_too_long,
            "in_flight": in_flight,
            "preemptions": sim.preemptions,
            "router_retries": sim.router_retries,
        },
        "predicted": _serve_pcts(details),
        "slo_attainment": round(attained / offered, 6) if offered else 1.0,
        "autoscale": sim.autoscale_log,
        "sim": {
            "mode": "serve",
            "seed": int(seed),
            "n_arrivals": offered,
            "pricing": pricer.mode,
            "policy": policy.describe(),
        },
    }
    validate_record(record)
    requests_doc = {
        "taxonomy": "serve",
        "counts": {
            "in_flight": in_flight,
            "finalized": len(sim.finalized),
            "ring": len(details),
            "evicted": 0,
            "by_state": {"done": len(sim.finalized)},
            "rejected": sim.rejected + sim.rejected_too_long,
        },
        "in_flight": [],
        "recent": details,
    }
    return record, requests_doc


# ------------------------------------------------------ serve validation


#: Percentiles REPORTED by ``--serve --validate``.
SERVE_PCT_KEYS = (
    "ttft_p50", "ttft_p95", "ttft_p99", "e2e_p50", "e2e_p95", "e2e_p99",
)

#: Percentiles GATED by default: p50/p95 only - on a smoke-sized run
#: (tens of requests) the p99 IS the sample maximum, dominated by one-off
#: host hiccups no seeded replay can reproduce; it is still printed.
SERVE_PCT_GATE_KEYS = (
    "ttft_p50", "ttft_p95", "e2e_p50", "e2e_p95",
)


def compare_serve_percentiles(
    predicted_details: list, measured_details: list, *,
    keys=SERVE_PCT_GATE_KEYS, tol: float = 0.5,
) -> list:
    """Relative TTFT/E2E percentile agreement between simulated and
    measured per-request details. Returns violation strings (empty =
    within tolerance); percentiles and tails via the same
    `reqtrace.decompose` arithmetic on both sides."""
    violations = []
    for key in keys:
        metric, _, qs = key.partition("_p")
        q = float(qs) / 100.0
        dp = _serve_decompose(predicted_details, metric, q)
        dm = _serve_decompose(measured_details, metric, q)
        if dp is None or dm is None:
            violations.append(
                f"percentile '{key}': "
                f"{'predicted' if dp is None else 'measured'} side has "
                f"no finished requests"
            )
            continue
        vp, vm = dp["value"], dm["value"]
        denom = max(abs(vm), 1e-9)
        rel = abs(vp - vm) / denom
        if rel > tol:
            violations.append(
                f"percentile '{key}': predicted {vp:.4f}s vs measured "
                f"{vm:.4f}s (rel diff {rel:.2f} > tol {tol:.2f})"
            )
    return violations


def arrivals_from_client_rows(client_rows, request_records=()) -> list:
    """Reconstruct the arrival stream of a measured loadgen run from
    ``--out-requests`` JSONL rows (send timestamps, relative to the
    first) joined with per-request records (prompt/max-token mix) by
    ``req_id``."""
    by_id = {
        r.get("req_id"): r for r in request_records or ()
        if isinstance(r, dict)
    }
    rows = [
        r for r in client_rows or ()
        if isinstance(r, dict) and r.get("t_send_unix")
    ]
    if not rows:
        return []
    t0 = min(float(r["t_send_unix"]) for r in rows)
    out = []
    for r in sorted(rows, key=lambda r: (float(r["t_send_unix"]),
                                         str(r.get("req_id")))):
        det = by_id.get(r.get("req_id")) or {}
        out.append({
            "t_s": round(float(r["t_send_unix"]) - t0, 9),
            "prompt_len": max(int(det.get("prompt_len") or 1), 1),
            "max_new_tokens": max(
                int(det.get("max_new_tokens")
                    or det.get("tokens_emitted") or r.get("n_tokens")
                    or 1), 1,
            ),
        })
    return out


def predict_serve_from_run(
    measured_record: dict,
    request_records: list, *,
    arrivals=None,
    client_rows=None,
    seed: int = 0,
):
    """Close the serve loop: replay a MEASURED run's exact arrivals and
    geometry through the twin, pricing ticks from the run's own
    per-request records (`extract_serve_distributions`). Returns
    ``(sim_record, requests_doc)``; gate with `compare_records`
    (bucket shares) + `compare_serve_percentiles` (TTFT/E2E tails)."""
    validate_record(measured_record)
    if measured_record.get("taxonomy") != "serve":
        raise ValueError(
            "not a serve-taxonomy record (taxonomy="
            f"{measured_record.get('taxonomy')!r}); serve validation "
            "needs the server's --run-record output"
        )
    dists = Distributions(
        extract_serve_distributions(request_records, client_rows)
    )
    if arrivals is not None:
        stream = load_arrivals(arrivals)
    else:
        stream = arrivals_from_client_rows(client_rows, request_records)
    if not stream:
        raise ValueError(
            "no arrivals to replay (need --arrival-trace output, or "
            "client rows from loadgen --out-requests)"
        )
    policy = ServePolicy.from_record(measured_record, replicas=1)
    rec, reqdoc = simulate_serve(
        policy, stream,
        dists=dists,
        seed=seed,
        wall_s=float(measured_record.get("wall_s") or 0.0) or None,
    )
    rec["sim"]["mode"] = "serve-validate"
    rec["sim"]["n_measured_requests"] = len(request_records or ())
    return rec, reqdoc


# ------------------------------------------------- dynamic capacity plan


def replicas_for_dynamic(
    manifest: dict, *,
    hw: str = "cpu-host",
    rate_rps: float,
    slo: dict,
    mean_new_tokens: int = 16,
    prompt_len: int = 8,
    dists: Distributions | None = None,
    n_requests: int = 200,
    seed: int = 0,
    max_replicas: int = 64,
) -> dict:
    """The DYNAMIC replica answer `cost.replicas_for_target` can't give:
    starting AT the static throughput floor (so the dynamic answer is
    >= it by construction), simulate fixed-size fleets under queueing at
    ``rate_rps`` until every SLO gate (``{"ttft_p99": 0.5, ...}``)
    holds on the simulated percentiles. Returns ``{"static": ...,
    "dynamic": {"replicas", "met", "gates"}, "curve": [...]}`` - the
    static floor is reported alongside, never silently replaced."""
    from .cost import HARDWARE_MODELS, replicas_for_target, serve_capacity

    capacity = (manifest.get("capacity") or {}).get(hw) \
        or serve_capacity(manifest, HARDWARE_MODELS[hw])
    target_ttft = slo.get("ttft_p99") or slo.get("ttft_p95") \
        or slo.get("ttft_p50")
    static = replicas_for_target(
        capacity,
        target_rps=rate_rps,
        mean_new_tokens=mean_new_tokens,
        prompt_len=prompt_len,
        target_ttft_s=target_ttft,
    )
    arrivals = synthesize_arrivals(
        rate_rps,
        n_requests=n_requests,
        prompt_lens=(prompt_len,),
        max_new=mean_new_tokens,
        seed=seed,
        dists=dists,
    )
    floor = max(int(static.get("replicas") or 1), 1)
    curve = []
    dynamic = None
    for n in range(floor, max_replicas + 1):
        policy = ServePolicy.from_manifest(
            manifest, replicas=n, slo=dict(slo)
        )
        rec, _ = simulate_serve(
            policy, arrivals,
            dists=dists, manifest=manifest, hw=hw, seed=seed,
        )
        gates = {}
        met = True
        for key, limit in sorted(slo.items()):
            metric, _, qs = key.partition("_p")
            pct = (rec["predicted"].get(metric) or {}).get(f"p{qs}")
            value = pct["value"] if pct else None
            ok = value is not None and value <= float(limit)
            gates[key] = {
                "value": value, "limit": float(limit), "met": ok,
            }
            met = met and ok
        done = rec["requests"]["completed"]
        met = met and done >= rec["requests"]["offered"] \
            - rec["requests"]["rejected_too_long"]
        curve.append({
            "replicas": n,
            "met": met,
            "gates": gates,
            "completed": done,
            "goodput_ratio": rec["goodput_ratio"],
            "slo_attainment": rec["slo_attainment"],
        })
        if met:
            dynamic = {"replicas": n, "met": True, "gates": gates}
            break
    if dynamic is None:
        dynamic = {
            "replicas": max_replicas,
            "met": False,
            "gates": curve[-1]["gates"] if curve else {},
            "why": f"SLO not met by {max_replicas} replicas "
                   "(kv/geometry-bound, not replica-bound?)",
        }
    return {
        "rate_rps": rate_rps,
        "slo": dict(slo),
        "static": static,
        "dynamic": dynamic,
        "curve": curve,
    }


# --------------------------------------------------- serve policy sweeps


def rank_serve_policies(
    policies: list, *,
    rate_rps: float = None,
    arrivals: list | None = None,
    dists: Distributions | None = None,
    manifest: dict | None = None,
    hw: str = "cpu-host",
    n_requests: int = 100,
    failure_rate_per_replica_per_h: float = 0.0,
    horizon_s: float = 3600.0,
    seeds=(0, 1),
) -> list:
    """Rank `ServePolicy` variants (`policy_variants` works on
    ServePolicy too - `with_` has the same contract) under COMMON
    random numbers: every policy sees the same seeded arrival streams
    and failure traces per seed. The metric is **SLO-attained
    completions per replica up-second** (``slo_per_capacity_s``) - the
    serving analogue of the training twin's surviving-progress metric:
    a policy that over-provisions its way to SLO pays for it in the
    denominator. Best first."""
    streams = []
    for s in seeds:
        if arrivals is not None:
            stream = arrivals
        else:
            if not rate_rps:
                raise ValueError("need rate_rps or arrivals")
            stream = synthesize_arrivals(
                rate_rps, n_requests=n_requests, seed=s, dists=dists,
            )
        trace = ()
        if failure_rate_per_replica_per_h > 0:
            trace = synthesize_failure_trace(
                max(policies[0].replicas, 1),
                rate_per_chip_per_h=failure_rate_per_replica_per_h,
                horizon_s=horizon_s, seed=s,
            )
        streams.append((s, stream, trace))
    out = []
    for policy in policies:
        recs = [
            simulate_serve(
                policy, stream,
                dists=dists, manifest=manifest, hw=hw,
                failure_trace=trace, seed=s,
            )[0]
            for s, stream, trace in streams
        ]
        per_cap = [
            (r["slo_attainment"] * r["requests"]["completed"])
            / r["wall_s"] if r["wall_s"] > 0 else 0.0
            for r in recs
        ]
        out.append({
            "policy": getattr(policy, "label", "") or "base",
            "slo_per_capacity_s": round(sum(per_cap) / len(per_cap), 9),
            "slo_attainment": round(
                sum(r["slo_attainment"] for r in recs) / len(recs), 6
            ),
            "completed": sum(r["requests"]["completed"] for r in recs),
            "rejected": sum(r["requests"]["rejected"] for r in recs),
            "preemptions": sum(
                r["requests"]["preemptions"] for r in recs
            ),
            "goodput_ratio": round(
                sum(r["goodput_ratio"] for r in recs) / len(recs), 6
            ),
            "wall_s": round(sum(r["wall_s"] for r in recs), 6),
        })
    out.sort(key=lambda d: -d["slo_per_capacity_s"])
    return out
