"""Abstract jaxpr tracing: enumerate collectives, upcasts, scan carries.

``collect_trace(closed_jaxpr)`` walks a step program's jaxpr recursively -
scan, while, cond, pjit, shard_map, remat, custom_{jvp,vjp} sub-jaxprs all
descend - and returns `TraceFacts`:

- every collective primitive (psum / all_gather / reduce_scatter /
  ppermute / all_to_all) with its mesh axes, per-call payload bytes, and
  STATIC multiplicity (scan bodies multiply by trip count; while bodies
  have no static count and are flagged ``dynamic`` - their bytes are
  reported PER ITERATION via ``dynamic_collective_bytes_per_iter`` and
  excluded from ``total_collective_bytes``, so a while-based decode loop
  can neither inflate nor silently zero out a per-step manifest total);
  each site additionally carries its provenance ``path`` (the jaxpr
  nesting it lives under, e.g. ``pjit/shard_map/scan[x4]`` - what
  ``tools/shardlint.py --explain`` prints), with the manifest-pinned
  ``collectives`` view merged across paths; ``pbroadcast`` /
  ``pcast`` are type casts that move no data and are not counted;
- every float-widening ``convert_element_type`` (bf16->f32, f32->f64, ...)
  with the same multiplicity accounting, plus any f64 result anywhere;
- each ``scan`` carry's byte footprint, and separately the carries of
  scans whose bodies issue a reduce_scatter (the ZeRO in-scan gradient
  accumulators - the replication-leak check compares them to D/dp);
- the jit boundary's ``donated_invars`` and flat input/output avals for
  the donation audit.

Byte convention (documented, so manifests are comparable): payload =
sum of INPUT aval bytes, except all_gather which counts its OUTPUT (the
materialized gathered buffer). These are logical payload bytes per call
per device shard-view, not wire bytes - a ring all-reduce moves
~2(n-1)/n of them (utils/tracing.py collective_bytes_per_sync).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field

import numpy as np

# primitive name -> canonical op name; jax renamed some across versions
# (the vma-era invariant variants, the pre-vma check_rep rewrite's psum2)
COLLECTIVE_PRIMS = {
    "psum": "psum",
    "psum2": "psum",
    "psum_invariant": "psum",
    "all_gather": "all_gather",
    "all_gather_invariant": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "ppermute": "ppermute",
    "pswapaxes": "all_to_all",
    "all_to_all": "all_to_all",
}


@dataclass(frozen=True)
class CollectiveSite:
    """One collective call site, multiplicity-weighted."""

    op: str
    axes: tuple  # sorted mesh axis names
    bytes_per_call: int
    count: int  # static multiplicity (scan trip counts folded in)
    dynamic: bool = False  # under a while loop: count is per-iteration
    path: str = ""  # provenance: jaxpr nesting, e.g. "pjit/shard_map/scan[x4]"

    @property
    def total_bytes(self) -> int:
        return self.bytes_per_call * self.count


@dataclass
class TraceFacts:
    collectives: list = field(default_factory=list)  # CollectiveSite, merged
    sites: list = field(default_factory=list)  # CollectiveSite, per call path
    upcasts: dict = field(default_factory=dict)  # "bf16->f32" -> {count, bytes}
    quant_dtypes: dict = field(default_factory=dict)  # "int8"/"fp8" -> count
    f64_sites: int = 0
    scan_carry_max_bytes: int = 0
    reduce_scatter_carry_bytes: int | None = None  # ZeRO in-scan accumulator
    donated_invars: tuple | None = None
    in_avals: list = field(default_factory=list)
    out_avals: list = field(default_factory=list)
    has_dynamic_loop: bool = False

    def total_collective_bytes(self) -> int:
        """Per-step bytes over STATIC sites only. Sites under a while loop
        (``dynamic=True``) have no static trip count - their per-iteration
        bytes are a separate figure (`dynamic_collective_bytes_per_iter`),
        never silently folded into (or zeroed out of) the per-step
        total a manifest pins."""
        return sum(c.total_bytes for c in self.collectives if not c.dynamic)

    def dynamic_collective_bytes_per_iter(self) -> int:
        """Bytes PER LOOP ITERATION of collectives under a while loop
        (e.g. a token-by-token decode loop); the trip count is runtime
        data, so there is no static per-step total for these."""
        return sum(c.total_bytes for c in self.collectives if c.dynamic)

    def op_totals(self) -> dict:
        out = {}
        for c in self.collectives:
            t = out.setdefault(c.op, {"count": 0, "bytes": 0})
            t["count"] += c.count
            t["bytes"] += c.total_bytes
        return out


def _np_dtype(dt):
    """numpy dtype or None (jax extended dtypes like key<fry> have none)."""
    try:
        return np.dtype(dt)
    except TypeError:
        return None


def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    if aval is None or not hasattr(aval, "shape"):
        return 0
    dt = _np_dtype(getattr(aval, "dtype", None))
    if dt is None:
        return 0
    return int(np.prod(aval.shape, dtype=np.int64)) * dt.itemsize


def _axes_of(params) -> tuple:
    axes = params.get("axes", params.get("axis_name", ()))
    if isinstance(axes, str):
        axes = (axes,)
    return tuple(sorted(str(a) for a in axes))


def _sub_jaxprs(eqn):
    """(sub_jaxpr, kind) pairs for every jaxpr-valued param of an eqn."""
    out = []
    for k, v in eqn.params.items():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            core = getattr(x, "jaxpr", x)
            if hasattr(core, "eqns"):
                out.append((core, k))
    return out


def _is_float(dt) -> bool:
    # jnp.issubdtype, not np: ml_dtypes floats (bfloat16, fp8) are not in
    # numpy's own type lattice
    import jax.numpy as jnp

    return jnp.issubdtype(dt, jnp.floating)


def _quant_dtype_name(dt) -> str | None:
    """Canonical low-precision family of a value dtype, or None.

    ``int8`` and the fp8 formats are the quantized-matmul storage
    dtypes (ops/quant.py); their presence in a trace marks a quantized
    step, which the precision lint requires to be DECLARED
    (meta["quant"]). uint8 is deliberately not counted - byte-valued
    DATA (token streams, image bytes) is not quantized compute."""
    if dt is None:
        return None
    name = dt.name
    if name == "int8":
        return "int8"
    if name.startswith("float8"):
        return "fp8"
    return None


def collect_trace(closed_jaxpr) -> TraceFacts:
    """Walk a ClosedJaxpr (e.g. ``jax.make_jaxpr(step)(*abstract_args)``)
    and collect `TraceFacts`. Purely structural - nothing executes."""
    facts = TraceFacts()
    top = closed_jaxpr.jaxpr
    facts.in_avals = [getattr(v, "aval", None) for v in top.invars]
    facts.out_avals = [getattr(v, "aval", None) for v in top.outvars]

    # the jit boundary: the top-level eqn carrying donated_invars (there is
    # exactly one for a jitted step; pick the widest if several)
    best = None
    for eqn in top.eqns:
        if "donated_invars" in eqn.params:
            if best is None or len(eqn.invars) > len(best.invars):
                best = eqn
    if best is not None:
        facts.donated_invars = tuple(best.params["donated_invars"])
        facts.out_avals = [getattr(v, "aval", None) for v in best.outvars]

    # (op, axes, bytes, dynamic, provenance path) -> count
    raw = defaultdict(int)

    def walk(jaxpr, mult: int, dynamic: bool, path: str):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            op = COLLECTIVE_PRIMS.get(name)
            if op is not None:
                if op == "all_gather":
                    nbytes = sum(_aval_bytes(v) for v in eqn.outvars)
                else:
                    nbytes = sum(_aval_bytes(v) for v in eqn.invars)
                raw[(op, _axes_of(eqn.params), nbytes, dynamic, path)] += mult
            elif name == "convert_element_type":
                src_aval = getattr(eqn.invars[0], "aval", None)
                src = _np_dtype(getattr(src_aval, "dtype", None))
                dst = _np_dtype(eqn.params.get("new_dtype"))
                if (
                    src is not None and dst is not None
                    and _is_float(src) and _is_float(dst)
                    and dst.itemsize > src.itemsize
                ):
                    key = f"{src.name}->{dst.name}"
                    rec = facts.upcasts.setdefault(
                        key, {"count": 0, "bytes": 0}
                    )
                    rec["count"] += mult
                    rec["bytes"] += mult * sum(
                        _aval_bytes(v) for v in eqn.outvars
                    )
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                dt = _np_dtype(getattr(aval, "dtype", None))
                if dt is not None and dt == np.float64:
                    facts.f64_sites += mult
                qname = _quant_dtype_name(dt)
                if qname is not None:
                    facts.quant_dtypes[qname] = (
                        facts.quant_dtypes.get(qname, 0) + mult
                    )

            if name == "scan":
                body = eqn.params["jaxpr"].jaxpr
                nc, nk = eqn.params["num_consts"], eqn.params["num_carry"]
                carry = sum(
                    _aval_bytes(v) for v in eqn.invars[nc:nc + nk]
                )
                facts.scan_carry_max_bytes = max(
                    facts.scan_carry_max_bytes, carry
                )
                if _contains_op(body, "reduce_scatter"):
                    prev = facts.reduce_scatter_carry_bytes or 0
                    facts.reduce_scatter_carry_bytes = max(prev, carry)
                length = int(eqn.params["length"])
                walk(
                    body, mult * length, dynamic,
                    _join(path, f"scan[x{length}]"),
                )
            elif name == "while":
                facts.has_dynamic_loop = True
                for sub, _ in _sub_jaxprs(eqn):
                    walk(sub, mult, True, _join(path, "while"))
            else:
                for sub, _ in _sub_jaxprs(eqn):
                    walk(sub, mult, dynamic, _join(path, name))

    walk(top, 1, False, "")
    facts.sites = sorted(
        (
            CollectiveSite(
                op=op, axes=axes, bytes_per_call=nbytes, count=count,
                dynamic=dyn, path=path,
            )
            for (op, axes, nbytes, dyn, path), count in raw.items()
        ),
        key=lambda c: (c.op, c.axes, -c.bytes_per_call, c.dynamic, c.path),
    )
    # merged view (stable across refactors that only move a site between
    # enclosing jaxprs) - what manifests pin; `sites` keeps provenance
    merged = defaultdict(int)
    for c in facts.sites:
        merged[(c.op, c.axes, c.bytes_per_call, c.dynamic)] += c.count
    facts.collectives = sorted(
        (
            CollectiveSite(
                op=op, axes=axes, bytes_per_call=nbytes, count=count,
                dynamic=dyn,
            )
            for (op, axes, nbytes, dyn), count in merged.items()
        ),
        key=lambda c: (c.op, c.axes, -c.bytes_per_call, c.dynamic),
    )
    return facts


def _join(path: str, label: str) -> str:
    return f"{path}/{label}" if path else label


def _contains_op(jaxpr, op: str) -> bool:
    for eqn in jaxpr.eqns:
        if COLLECTIVE_PRIMS.get(eqn.primitive.name) == op:
            return True
        for sub, _ in _sub_jaxprs(eqn):
            if _contains_op(sub, op):
                return True
    return False
