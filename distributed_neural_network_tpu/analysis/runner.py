"""The shardlint driver: build -> trace -> lint -> manifest write/check.

Library API behind tools/shardlint.py and tests/test_shardlint.py:

    result = analyze_program(program)        # one StepProgram
    rc, report = run_shardlint(["lm_zero_overlap"], mode="check")

``run_shardlint`` returns a process-style exit code (0 conforming,
1 findings/diffs, 2 config could not be built/traced) plus a printable
report, so the CLI is a thin argv wrapper.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from .configs import build_program, config_names
from .lint import lint_program
from .manifest import (
    build_manifest,
    diff_manifests,
    load_manifest,
    save_manifest,
)
from .trace import collect_trace


@dataclass
class AnalysisResult:
    program: object
    facts: object
    manifest: dict
    findings: list = field(default_factory=list)

    @property
    def errors(self) -> list:
        return [f for f in self.findings if f.severity == "error"]


def analyze_program(program) -> AnalysisResult:
    """Trace one StepProgram and run every lint family over it."""
    facts = collect_trace(program.make_jaxpr())
    return AnalysisResult(
        program=program,
        facts=facts,
        manifest=build_manifest(program, facts),
        findings=lint_program(program, facts),
    )


def explain_sites(facts) -> list:
    """Per-collective-site provenance table lines: op, axes, bytes/call,
    static multiplicity, dynamic flag, and WHERE the site lives (the
    jaxpr nesting recorded by the tracer) - ``shardlint --explain``."""
    if not facts.sites:
        return ["    (no collective sites)"]
    lines = [
        f"    {'op':<16} {'axes':<12} {'B/call':>10} {'count':>6} "
        f"{'dyn':>4}  where"
    ]
    for c in facts.sites:
        lines.append(
            f"    {c.op:<16} {','.join(c.axes) or '-':<12} "
            f"{c.bytes_per_call:>10,} {c.count:>6} "
            f"{'yes' if c.dynamic else '-':>4}  {c.path or '(top level)'}"
        )
    dyn = facts.dynamic_collective_bytes_per_iter()
    if dyn:
        lines.append(
            f"    dynamic sites move {dyn:,} B per while-loop iteration "
            "(excluded from the per-step total)"
        )
    return lines


def _run_one_config(
    name: str,
    mode: str,
    manifest_dir: str | None,
    verbose: bool,
    explain: bool,
):
    """One config's full build -> trace -> lint -> manifest pass:
    (exit_code, report_lines). Self-contained so `run_shardlint` can
    fan configs out over worker threads (tracing is abstract and
    side-effect free; `compat.trace_compat` keeps its state
    thread-local; manifest writes land in per-config files)."""
    t0 = time.perf_counter()
    try:
        program = build_program(name)
        result = analyze_program(program)
    except Exception as e:
        return 2, [f"{name}: TRACE FAILED - {type(e).__name__}: {e}"]
    dt = time.perf_counter() - t0
    rc = 0
    lines = []
    facts = result.facts
    summary = (
        f"{name}: {sum(c.count for c in facts.collectives)} collective "
        f"call(s), {facts.total_collective_bytes():,} B/step, "
        f"{len(result.findings)} finding(s) [{dt:.1f}s]"
    )
    if explain:
        lines.append(summary)
        lines.extend(explain_sites(facts))
    elif verbose:
        lines.append(summary)
        for c in facts.collectives:
            dyn = " DYNAMIC" if c.dynamic else ""
            lines.append(
                f"    {c.op:<16} axes={','.join(c.axes) or '-'}  "
                f"x{c.count:<4} {c.bytes_per_call:>10,} B/call{dyn}"
            )
    for f in result.findings:
        lines.append(f"    {f}")
    if result.errors:
        rc = 1
    if mode == "write":
        if result.errors:
            lines.append(
                f"    {name}: NOT writing manifest while lint errors "
                "are outstanding"
            )
        else:
            path = save_manifest(result.manifest, name, manifest_dir)
            lines.append(f"    wrote {path}")
    elif mode == "check":
        try:
            expected = load_manifest(name, manifest_dir)
        except FileNotFoundError as e:
            return max(rc, 1), lines + [f"    {e}"]
        diffs = diff_manifests(expected, result.manifest)
        if diffs:
            rc = max(rc, 1)
            lines.append(f"    {name}: MANIFEST MISMATCH:")
            lines.extend(f"      - {d}" for d in diffs)
        else:
            lines.append(f"    manifest conforms ({name}.json)")
    return rc, lines


def run_shardlint(
    names=None,
    *,
    mode: str = "lint",
    manifest_dir: str | None = None,
    verbose: bool = True,
    explain: bool = False,
    jobs: int = 1,
):
    """Analyze configs; mode: 'lint' (no manifest I/O), 'write' (regenerate
    manifests), 'check' (diff against checked-in manifests). Returns
    (exit_code, report_str). ``explain=True`` prints the per-site
    provenance table (op, axes, bytes, multiplicity, enclosing jaxprs)
    instead of the merged per-collective summary.

    ``jobs > 1`` traces configs on a thread pool (abstract tracing
    holds the GIL only in bursts, so the serial full-sweep wall time -
    the CI static-check's dominant cost - drops with real parallelism
    on program-building numpy/XLA work). The report is rendered in
    input order regardless of completion order, so line order, verdicts,
    and the exit code match a serial run (only the per-config wall-time
    stamps differ)."""
    if mode not in ("lint", "write", "check"):
        raise ValueError(f"mode must be lint/write/check, got {mode!r}")
    names = list(names) if names else config_names()
    jobs = max(1, int(jobs))
    if jobs == 1 or len(names) <= 1:
        results = [
            _run_one_config(name, mode, manifest_dir, verbose, explain)
            for name in names
        ]
    else:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(jobs, len(names)),
            thread_name_prefix="shardlint",
        ) as pool:
            results = list(pool.map(
                lambda name: _run_one_config(
                    name, mode, manifest_dir, verbose, explain
                ),
                names,
            ))
    worst = max((rc for rc, _ in results), default=0)
    lines = [ln for _, chunk in results for ln in chunk]
    status = {0: "OK", 1: "FAIL", 2: "TRACE ERROR"}[worst]
    lines.append(f"shardlint: {len(names)} config(s), {status}")
    return worst, "\n".join(lines)
