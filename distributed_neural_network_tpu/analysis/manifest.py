"""Expected-collectives manifests: the checked-in contract per config.

A manifest (analysis/manifests/<config>.json) pins, for one canonical
train-step config, exactly which collectives the compiled step issues -
op, mesh axes, payload bytes per call, static call count - plus the dtype
upcasts, the donation contract, and the scan-carry footprints.
``--check`` re-traces the config and diffs against the manifest: an
accidental extra all-gather, a de-bucketed reduce, or a dropped donation
fails statically with the op, axes, and byte count named.

Manifests are jax-version-stamped: the traced program differs across jax
generations (pre-``jax.shard_map`` builds trace without the vma-typed
autodiff psums - see compat.py), so a version change requires
regenerating with ``--write-manifest`` (docs/STATIC_ANALYSIS.md). CI pins
the version for exactly this reason.
"""

from __future__ import annotations

import json
import os

MANIFEST_SCHEMA = 1


def default_manifest_dir() -> str:
    return os.path.join(os.path.dirname(__file__), "manifests")


def manifest_path(name: str, manifest_dir: str | None = None) -> str:
    return os.path.join(manifest_dir or default_manifest_dir(), f"{name}.json")


def build_manifest(program, facts) -> dict:
    """The manifest document for one traced program."""
    import jax

    donated = facts.donated_invars
    return {
        "schema": MANIFEST_SCHEMA,
        "config": program.name,
        "jax_version": jax.__version__,
        "trace_mode": _trace_mode(),
        "mesh": {k: int(v) for k, v in program.mesh.shape.items()},
        "meta": _jsonable(program.meta),
        "param_bytes": program.param_bytes(),
        "collectives": [
            {
                "op": c.op,
                "axes": list(c.axes),
                "bytes_per_call": int(c.bytes_per_call),
                "count": int(c.count),
                "total_bytes": int(c.total_bytes),
                **({"dynamic": True} if c.dynamic else {}),
            }
            for c in facts.collectives
        ],
        "collective_totals": facts.op_totals(),
        "total_collective_bytes": facts.total_collective_bytes(),
        "dynamic_collective_bytes_per_iter": (
            facts.dynamic_collective_bytes_per_iter()
        ),
        "upcasts": {
            k: dict(v) for k, v in sorted(facts.upcasts.items())
        },
        "quant_dtypes": {
            k: int(v) for k, v in sorted(
                (getattr(facts, "quant_dtypes", None) or {}).items()
            )
        },
        "donation": {
            "argnums": list(program.donate),
            "n_donated": int(sum(donated)) if donated is not None else None,
            "n_args": len(donated) if donated is not None else None,
        },
        "scan_carry_max_bytes": int(facts.scan_carry_max_bytes),
        "reduce_scatter_carry_bytes": (
            int(facts.reduce_scatter_carry_bytes)
            if facts.reduce_scatter_carry_bytes is not None else None
        ),
        "has_dynamic_loop": bool(facts.has_dynamic_loop),
    }


def save_manifest(doc: dict, name: str, manifest_dir: str | None = None) -> str:
    path = manifest_path(name, manifest_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True, allow_nan=False)
        f.write("\n")
    return path


def load_manifest(name: str, manifest_dir: str | None = None) -> dict:
    path = manifest_path(name, manifest_dir)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no manifest for config {name!r} at {path} - generate one "
            f"with: python tools/shardlint.py --config {name} "
            "--write-manifest"
        )
    with open(path) as f:
        return json.load(f)


def _coll_key(c: dict) -> tuple:
    return (c["op"], tuple(c["axes"]), int(c["bytes_per_call"]),
            bool(c.get("dynamic", False)))


def _fmt_coll(c: dict) -> str:
    dyn = " (dynamic trip count)" if c.get("dynamic") else ""
    return (
        f"{c['op']} over axes {tuple(c['axes'])}, "
        f"{c['bytes_per_call']:,} B/call x{c['count']}{dyn}"
    )


def diff_manifests(expected: dict, actual: dict) -> list:
    """Human-actionable differences (empty list == conforming).

    Environment mismatches (jax version / trace mode) short-circuit with a
    regenerate instruction instead of producing a confusing byte diff.
    """
    msgs = []
    for key in ("jax_version", "trace_mode"):
        if expected.get(key) != actual.get(key):
            return [
                f"manifest for {expected.get('config')!r} was written under "
                f"{key}={expected.get(key)!r} but this run has "
                f"{key}={actual.get(key)!r}: the traced program is not "
                "comparable across jax generations - regenerate with "
                "--write-manifest (docs/STATIC_ANALYSIS.md)"
            ]
    if expected.get("mesh") != actual.get("mesh"):
        return [
            f"mesh mismatch: manifest {expected.get('mesh')} vs traced "
            f"{actual.get('mesh')} - regenerate or fix the config"
        ]
    exp = {_coll_key(c): c for c in expected.get("collectives", [])}
    act = {_coll_key(c): c for c in actual.get("collectives", [])}
    for key in sorted(set(exp) | set(act), key=str):
        e, a = exp.get(key), act.get(key)
        if e is None:
            msgs.append(f"EXTRA collective not in manifest: {_fmt_coll(a)}")
        elif a is None:
            msgs.append(f"MISSING collective from manifest: {_fmt_coll(e)}")
        elif e["count"] != a["count"]:
            msgs.append(
                f"collective count changed: {_fmt_coll(e)} -> x{a['count']}"
            )
    if expected.get("upcasts") != actual.get("upcasts"):
        msgs.append(
            f"dtype upcasts changed: manifest {expected.get('upcasts')} vs "
            f"traced {actual.get('upcasts')}"
        )
    # quantized-dtype pins (int8/fp8 value counts): a quantized config
    # whose fast path falls back - or a full-precision config that grows
    # a low-precision cast - diffs here (legacy manifests lack the key:
    # missing compares as empty, so unquantized configs need no rewrite)
    eq = expected.get("quant_dtypes") or {}
    aq = actual.get("quant_dtypes") or {}
    if eq != aq:
        msgs.append(
            f"quantized dtypes changed: manifest {eq or '{}'} vs traced "
            f"{aq or '{}'} - the low-precision contract moved (lint "
            "codes quant-undeclared / quant-missing)"
        )
    eb = expected.get("total_collective_bytes")
    ab = actual.get("total_collective_bytes")
    if eb != ab and not any(m.startswith(("EXTRA", "MISSING", "collective"))
                            for m in msgs):
        msgs.append(
            f"total collective bytes changed: {eb:,} -> {ab:,} per step"
        )
    # dynamic (while-loop) sites are excluded from the per-step total and
    # compared on their own per-iteration figure, so a decode-style loop
    # can never zero a manifest silently (older manifests lack the key)
    edyn = expected.get("dynamic_collective_bytes_per_iter", 0) or 0
    adyn = actual.get("dynamic_collective_bytes_per_iter", 0) or 0
    if edyn != adyn and not any(
        m.startswith(("EXTRA", "MISSING", "collective")) for m in msgs
    ):
        msgs.append(
            f"dynamic (while-loop) collective bytes changed: {edyn:,} -> "
            f"{adyn:,} per loop iteration"
        )
    ed, ad = expected.get("donation") or {}, actual.get("donation") or {}
    if ed != ad:
        msgs.append(f"donation contract changed: manifest {ed} vs traced {ad}")
    er = expected.get("reduce_scatter_carry_bytes")
    ar = actual.get("reduce_scatter_carry_bytes")
    if er != ar:
        msgs.append(
            f"ZeRO in-scan carry changed: manifest {er} B vs traced {ar} B"
        )
    return msgs


def _trace_mode() -> str:
    from .. import compat

    return compat.trace_mode()


def _jsonable(x):
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    return str(x)
