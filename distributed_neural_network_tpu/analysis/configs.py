"""Canonical train-step configs the static analyzer traces.

One entry per parallel regime x gradient-sync schedule the framework
ships: dp / tp / zero / zero-adam / pp, each under the end and (where it
exists) overlap schedules, plus the CNN engine's fused epoch program. Each
builder returns a `StepProgram` (train/program.py) over a TINY model - the
analyzer pins collective STRUCTURE (which ops, which axes, how many, in
what ratio to the parameter bytes), not production shapes, so traces stay
sub-second on a laptop CPU and the manifests stay readable.

All builders run under ``compat.trace_compat()`` so they work on jax
builds without ``jax.shard_map`` (the step is only traced, never
executed - compat.py).

Meshes use 8 devices (the repo-standard
``--xla_force_host_platform_device_count=8`` virtual CPU mesh; tests get
it from conftest.py, tools/shardlint.py sets it before importing jax).
"""

from __future__ import annotations

import jax

from .. import compat

# tiny trace model: big enough that every leaf family (embed/head/norms/
# attention/mlp) is present and dims divide an 8-device mesh, small enough
# to trace in well under a second
TRACE_VOCAB = 64
TRACE_D_MODEL = 32
TRACE_HEADS = 4
TRACE_LAYERS = 2
TRACE_D_FF = 64
TRACE_BATCH = 8
TRACE_SEQ = 16
# small cap so the tiny tree still splits into >1 bucket per spec group -
# the overlap manifests then pin the BUCKETED shape of the schedule
TRACE_BUCKET_MB = 0.002


def _trace_cfg(**cfg_kwargs):
    from ..models import transformer as tfm

    return tfm.TransformerConfig(
        vocab_size=TRACE_VOCAB, d_model=TRACE_D_MODEL, n_heads=TRACE_HEADS,
        n_layers=TRACE_LAYERS, d_ff=TRACE_D_FF, **cfg_kwargs,
    )


def _require_devices(n: int):
    if jax.device_count() < n:
        raise RuntimeError(
            f"shardlint configs need {n} devices, have {jax.device_count()} "
            "- run with XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "set BEFORE jax is imported (tools/shardlint.py does this)"
        )


# config name -> the structured recipe behind its builder: family, mesh
# factors, optimizer, extra step kwargs. The autoshard search
# (analysis/autoshard.py) re-builds the same program at OTHER mesh
# factorizations from these, so search candidates can never drift from
# what shardlint traces.
BLUEPRINTS: dict = {}


def _lm(name, *, dp=4, sp=1, tp=1, optimizer="sgd", cfg_kwargs=None, **kw):
    from ..train import lm as lmtrain

    BLUEPRINTS[name] = {
        "family": "lm", "dp": dp, "sp": sp, "tp": tp,
        "optimizer": optimizer, "kwargs": dict(kw),
        "cfg_kwargs": dict(cfg_kwargs or {}),
    }

    def build():
        _require_devices(dp * sp * tp)
        cfg = _trace_cfg(**(cfg_kwargs or {}))
        mesh = lmtrain.create_lm_mesh(dp, sp, tp)
        with compat.trace_compat():
            return lmtrain.lm_step_program(
                cfg, mesh, batch=TRACE_BATCH, seq_len=TRACE_SEQ, name=name,
                optimizer=optimizer, bucket_mb=TRACE_BUCKET_MB, **kw,
            )

    return build


def _pp(name, *, dp=2, pp=2, optimizer="sgd", **kw):
    from ..parallel import pipeline as ppl

    BLUEPRINTS[name] = {
        "family": "pp", "dp": dp, "pp": pp, "tp": 1,
        "optimizer": optimizer,
        "kwargs": dict(kw, n_microbatches=2),
    }

    def build():
        _require_devices(dp * pp)
        cfg = _trace_cfg()
        mesh = ppl.create_pp_mesh(dp, pp, 1)
        with compat.trace_compat():
            return ppl.pp_step_program(
                cfg, mesh, batch=TRACE_BATCH, seq_len=TRACE_SEQ, name=name,
                optimizer=optimizer, n_microbatches=2,
                bucket_mb=TRACE_BUCKET_MB, **kw,
            )

    return build


def _reshard(name, *, dp=4):
    from ..parallel import reshard
    from ..train import lm as lmtrain

    def build():
        _require_devices(dp)
        cfg = _trace_cfg()
        mesh = lmtrain.create_lm_mesh(dp, 1, 1)
        with compat.trace_compat():
            return reshard.reshard_step_program(cfg, mesh, name=name)

    return build


def _reshard_pp(name, *, dp=2, pp=2):
    from ..parallel import pipeline as ppl, reshard

    def build():
        _require_devices(dp * pp)
        cfg = _trace_cfg()
        mesh = ppl.create_pp_mesh(dp, pp, 1)
        with compat.trace_compat():
            return reshard.reshard_pp_step_program(cfg, mesh, name=name)

    return build


def _cnn(name, phase):
    def build():
        _require_devices(4)
        from ..data.cifar10 import load_split
        from ..train.engine import Engine, TrainConfig

        with compat.trace_compat():
            engine = Engine(
                TrainConfig(nb_proc=4, batch_size=8, epochs=1),
                load_split(True, source="synthetic", synthetic_size=64),
                None,
            )
            progs = {p.name: p for p in engine.step_programs()}
        if phase not in progs:
            raise RuntimeError(
                f"{name}: engine exposed no {phase!r} program "
                f"(has {list(progs)})"
            )
        prog = progs[phase]
        object.__setattr__(prog, "name", name)
        return prog

    return build


OVERLAP = dict(accum_steps=2, grad_sync="overlap")

CANONICAL_CONFIGS = {
    # dp: replicated params, grad sync over 'data' (+ the end/overlap pair)
    "lm_dp": _lm("lm_dp"),
    "lm_dp_overlap": _lm("lm_dp_overlap", **OVERLAP),
    # adam: same sync, 2x state in the donation contract
    "lm_adam": _lm("lm_adam", optimizer="adam"),
    # tp: per-block forward psums over 'model'
    "lm_tp": _lm("lm_tp", dp=2, tp=2),
    # ZeRO-1 family: per-leaf all_gather reassembly; overlap adds the
    # in-scan bucketed reduce-scatter with the O(D/dp) shard carry
    "lm_zero": _lm("lm_zero", optimizer="zero"),
    "lm_zero_overlap": _lm("lm_zero_overlap", optimizer="zero", **OVERLAP),
    "lm_zero_adam": _lm("lm_zero_adam", optimizer="zero-adam"),
    "lm_zero_adam_overlap": _lm(
        "lm_zero_adam_overlap", optimizer="zero-adam", **OVERLAP
    ),
    # the fp8/int8 fast path (ROADMAP item 3): the same dp step with
    # quantized attention matmuls - the manifest pins the int8/fp8 value
    # counts AND the wide-accumulate upcasts (fp8->f32 appears in the
    # upcast table), so a silently-dropped low-precision path or a
    # silently-dropped accumulation upcast both fail --check
    "lm_quant_fp8": _lm(
        "lm_quant_fp8", cfg_kwargs=dict(attn_quant="fp8")
    ),
    "lm_quant_int8": _lm(
        "lm_quant_int8", cfg_kwargs=dict(attn_quant="int8")
    ),
    # pipeline: per-tick ppermute ring + the exit all_to_all
    "pp_gpipe": _pp("pp_gpipe"),
    "pp_overlap": _pp("pp_overlap", **OVERLAP),
    "pp_zero": _pp("pp_zero", optimizer="zero"),
    # elastic resharder (parallel/reshard.py): the same-mesh collective
    # form of the ZeRO reassembly - one tiled all_gather per state leaf
    # over 'data' - so the reshard transfer's collective bytes are pinned
    # like every training step's
    "lm_reshard_zero_gather": _reshard("lm_reshard_zero_gather"),
    # the ZeRO-under-pp resharder: per pipe-sharded leaf one data-axis
    # segment gather + one pipe-axis stage concat (stage order explicit),
    # per replicated leaf the mesh path's single data gather - pinned so
    # the elastic path's transfer schedule cannot regress silently
    "pp_reshard_zero_gather": _reshard_pp("pp_reshard_zero_gather"),
    # the CNN engine: the sharded local-SGD epoch (no collectives by
    # design - local training) and the fault-masked parameter-average
    # sync phase (where the epoch-edge psums live)
    "cnn_dp": _cnn("cnn_dp", "cnn_train_epoch"),
    "cnn_sync": _cnn("cnn_sync", "cnn_sync"),
}


def config_names() -> list:
    return list(CANONICAL_CONFIGS)


def searchable_config_names() -> list:
    """Configs the autoshard search covers: the lm/pp TRAINING steps,
    whose mesh factorization is a free choice. The CNN engine's programs
    (batch-axis only) and the reshard transfer program (mesh fixed by the
    checkpoint) have nothing to search over."""
    return [n for n, bp in BLUEPRINTS.items() if bp["family"] in ("lm", "pp")]


def build_program(name: str):
    try:
        build = CANONICAL_CONFIGS[name]
    except KeyError:
        raise KeyError(
            f"unknown shardlint config {name!r}; known configs: "
            f"{', '.join(CANONICAL_CONFIGS)}"
        ) from None
    return build()
