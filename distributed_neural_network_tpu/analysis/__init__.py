"""shardlint: static sharding, collective, and donation analysis.

Every sharding invariant in this framework used to be enforced only by
RUNNING the code - a wrong PartitionSpec, a dropped ``donate_argnums``, or
an accidental O(D) all-gather in the ZeRO path surfaced as a slow or OOM
run on real hardware. This package is the correctness gate that runs on
CPU, before a TPU ever sees the change (docs/STATIC_ANALYSIS.md):

- ``trace``    - abstractly trace a `StepProgram` (train/program.py) via
  ``jax.make_jaxpr`` - no execution, no devices beyond the host - and
  walk the closed jaxpr (descending into scan/while/cond/pjit/shard_map/
  remat sub-jaxprs) collecting every collective with its axes, payload
  bytes, and static multiplicity, every dtype upcast, and every scan
  carry footprint.
- ``lint``     - spec lint (axes exist, no duplicate axis, divisible
  dims; parallel/partition.py validators), donation audit (state args
  donated and aliasable), ZeRO replication-leak check (the in-scan
  gradient carry really is O(D/dp)), and precision lint (no f64 on the
  hot path).
- ``manifest`` - the expected-collectives contract: a checked-in JSON
  per canonical config (analysis/manifests/*.json) that ``--check``
  diffs fresh traces against, so an extra all-gather or a de-bucketed
  reduce fails statically with the op, axes, and byte count named.
- ``configs``  - the canonical train-step configs (dp/tp/zero/zero-adam/
  pp x grad_sync end/overlap, plus the CNN engine's epoch program), each
  with a structured BLUEPRINT the sharding search re-factors.
- ``runner``   - the library API behind tools/shardlint.py
  (``run_shardlint``).
- ``cost``     - the static cost model: score a traced plan's collective
  wire bytes, per-device state memory, donation coverage, and
  replication leaks - all from `TraceFacts`, nothing executed.
- ``autoshard`` - the ``--sharding auto`` search: enumerate mesh
  factorizations x rule-derived spec assignments x optimizer layouts,
  trace each candidate with ``trace``, score with ``cost``, pin the
  winner as a checked-in plan manifest (analysis/plans/*.json) that
  ``tools/autoshard.py --check`` gates in CI.
- ``fleetsim`` - the fleet digital twin: a deterministic discrete-event
  goodput simulator that replays a `SupervisorPolicy` over synthetic
  failure traces using cost-model step seconds (`cost.step_seconds`)
  and measured event-duration distributions (utils/goodput.py), ranks
  robustness policies and autoshard plans by goodput-under-failures,
  derives optimal checkpoint cadence (Young/Daly cross-checked), and
  validates itself against real ledger records
  (``tools/fleetsim.py --validate``, gated in CI).
- ``serve_trace`` - servelint, the serve-side mirror of the pipeline:
  enumerate the bucket grid ``warmup()`` compiles from an
  `EngineConfig` alone, trace every decode/prefill/draft/verify bucket
  program, lint the donation + quant contracts, pin per-bucket
  flops/HBM/gather/scatter facts into serve manifests, and price the
  ticks on the `cost.serve_tick_seconds` roofline - the capacity
  planner behind tools/servelint.py (``run_servelint``).
"""

from .autoshard import (
    build_plan_doc,
    diff_plans,
    load_plan,
    plan_path,
    run_autoshard,
    save_plan,
    search_config,
    search_plans,
)
from .configs import (
    BLUEPRINTS,
    CANONICAL_CONFIGS,
    build_program,
    config_names,
    searchable_config_names,
)
from .cost import (
    CostBreakdown,
    CostWeights,
    HARDWARE_MODELS,
    HardwareModel,
    StepTime,
    dense_step_flops,
    replicas_for_target,
    score_program,
    serve_capacity,
    serve_tick_seconds,
    step_seconds,
)
from .fleetsim import (
    Distributions,
    FailureEvent,
    SimPolicy,
    cadence_search,
    compare_records,
    policy_variants,
    predict_from_ledger,
    rank_plans_by_goodput,
    rank_policies,
    simulate,
    synthesize_failure_trace,
    young_daly_interval,
)
from .lint import Finding, lint_program
from .manifest import (
    MANIFEST_SCHEMA,
    build_manifest,
    default_manifest_dir,
    diff_manifests,
    load_manifest,
    manifest_path,
    save_manifest,
)
from .runner import analyze_program, run_shardlint
from .serve_trace import (
    SERVE_CONFIGS,
    SERVE_MANIFEST_SCHEMA,
    ServeBucketProgram,
    analyze_serve_program,
    bucket_programs,
    build_serve_engine,
    build_serve_manifest,
    collect_serve_costs,
    diff_serve_manifests,
    enumerate_grid,
    load_serve_manifest,
    run_servelint,
    save_serve_manifest,
    serve_config_names,
    static_decode_tokens_per_s,
)
from .trace import CollectiveSite, TraceFacts, collect_trace

__all__ = [
    "BLUEPRINTS",
    "CANONICAL_CONFIGS",
    "CollectiveSite",
    "CostBreakdown",
    "CostWeights",
    "Distributions",
    "FailureEvent",
    "Finding",
    "HARDWARE_MODELS",
    "HardwareModel",
    "MANIFEST_SCHEMA",
    "SERVE_CONFIGS",
    "SERVE_MANIFEST_SCHEMA",
    "ServeBucketProgram",
    "SimPolicy",
    "StepTime",
    "TraceFacts",
    "analyze_program",
    "analyze_serve_program",
    "bucket_programs",
    "build_manifest",
    "build_plan_doc",
    "build_program",
    "build_serve_engine",
    "build_serve_manifest",
    "cadence_search",
    "collect_serve_costs",
    "collect_trace",
    "compare_records",
    "config_names",
    "default_manifest_dir",
    "dense_step_flops",
    "diff_manifests",
    "diff_plans",
    "diff_serve_manifests",
    "enumerate_grid",
    "lint_program",
    "load_manifest",
    "load_plan",
    "load_serve_manifest",
    "manifest_path",
    "plan_path",
    "policy_variants",
    "predict_from_ledger",
    "rank_plans_by_goodput",
    "rank_policies",
    "replicas_for_target",
    "run_autoshard",
    "run_servelint",
    "run_shardlint",
    "save_manifest",
    "save_plan",
    "save_serve_manifest",
    "score_program",
    "search_config",
    "search_plans",
    "serve_capacity",
    "serve_config_names",
    "serve_tick_seconds",
    "simulate",
    "static_decode_tokens_per_s",
    "step_seconds",
    "synthesize_failure_trace",
    "young_daly_interval",
]
