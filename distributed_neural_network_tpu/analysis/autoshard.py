"""Autoshard: static cost-model search over mesh/spec/optimizer plans.

``--sharding auto`` as pure static analysis: enumerate the mesh
factorizations of a device count (dp x sp x tp for the LM family,
dp x pp for the pipeline family), derive each candidate's PartitionSpecs
from the declarative rule table (parallel/rules.py - a candidate with a
tensor axis activates the tp rules, one without deactivates them), build
the REAL step program for it (`train/lm.py lm_step_program` /
`parallel/pipeline.py pp_step_program` - the same builders training and
shardlint use), abstract-trace it with the shardlint tracer (trace.py),
and score it with the static cost model (cost.py). Nothing executes;
scoring a candidate costs one ``jax.make_jaxpr``.

Candidates whose builder or trace raises (non-divisible batch/seq/heads,
zero-with-tp, pipeline stages not dividing the layers) are pruned as
infeasible with the builder's own error as the reason; candidates over
the HBM budget are pruned by the cost model. The survivors are ranked by
score (ties broken by plan label, so ranking is deterministic) and the
winner is pinned as a checked-in PLAN manifest (analysis/plans/
<config>.json - same contract/diff idea as the collective manifests):
``tools/autoshard.py --check`` re-runs the search and fails if the top
plan drifted, exactly like shardlint's ``--check`` for collectives.

Plans record whether the winner matches the hand-written canonical mesh
(``matches_hand_config``); a blessed-better plan is a reviewed manifest
diff, not a silent change.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .. import compat
from .configs import (
    BLUEPRINTS,
    TRACE_BATCH,
    TRACE_BUCKET_MB,
    TRACE_SEQ,
    _require_devices,
    _trace_cfg,
    searchable_config_names,
)
from .cost import CostWeights, score_program
from .trace import collect_trace

PLAN_SCHEMA = 1


def default_plan_dir() -> str:
    return os.path.join(os.path.dirname(__file__), "plans")


def plan_path(name: str, plan_dir: str | None = None) -> str:
    return os.path.join(plan_dir or default_plan_dir(), f"{name}.json")


# ------------------------------------------------- candidate enumeration


def lm_mesh_candidates(n_devices: int) -> list:
    """Every ordered (dp, sp, tp) with dp*sp*tp == n_devices."""
    out = []
    for dp in range(1, n_devices + 1):
        if n_devices % dp:
            continue
        rest = n_devices // dp
        for sp in range(1, rest + 1):
            if rest % sp:
                continue
            out.append({"dp": dp, "sp": sp, "tp": rest // sp})
    return out


def pp_mesh_candidates(n_devices: int) -> list:
    """Every (dp, pp) with dp*pp == n_devices and at least two stages
    (a one-stage pipeline is the plain mesh family's ground)."""
    return [
        {"dp": n_devices // pp, "pp": pp}
        for pp in range(2, n_devices + 1)
        if n_devices % pp == 0
    ]


def _plan_label(family: str, dims: dict, optimizer: str) -> str:
    axes = "x".join(f"{k}{v}" for k, v in dims.items())
    return f"{family}:{axes}:{optimizer}"


def build_candidate_program(
    family: str,
    dims: dict,
    *,
    cfg,
    batch: int,
    seq_len: int,
    optimizer: str,
    kwargs: dict | None = None,
    name: str = "candidate",
):
    """The real step program for one candidate plan, built under
    ``compat.trace_compat()`` (trace-only, any jax build)."""
    kwargs = dict(kwargs or {})
    kwargs.setdefault("bucket_mb", TRACE_BUCKET_MB)
    if family == "lm":
        from ..train import lm as lmtrain

        _require_devices(dims["dp"] * dims["sp"] * dims["tp"])
        mesh = lmtrain.create_lm_mesh(dims["dp"], dims["sp"], dims["tp"])
        with compat.trace_compat():
            return lmtrain.lm_step_program(
                cfg, mesh, batch=batch, seq_len=seq_len, name=name,
                optimizer=optimizer, **kwargs,
            )
    if family == "pp":
        from ..parallel import pipeline as ppl

        _require_devices(dims["dp"] * dims["pp"])
        mesh = ppl.create_pp_mesh(dims["dp"], dims["pp"], 1)
        with compat.trace_compat():
            return ppl.pp_step_program(
                cfg, mesh, batch=batch, seq_len=seq_len, name=name,
                optimizer=optimizer, **kwargs,
            )
    raise ValueError(f"unknown plan family {family!r} (use 'lm' or 'pp')")


# ------------------------------------------------------------ the search


@dataclass
class RankedPlan:
    label: str
    family: str
    dims: dict
    optimizer: str
    breakdown: object = None  # CostBreakdown when traced
    infeasible_reason: str = ""

    @property
    def feasible(self) -> bool:
        return self.breakdown is not None and self.breakdown.feasible

    @property
    def score(self) -> float:
        return self.breakdown.score if self.feasible else float("inf")


@dataclass
class SearchResult:
    config: str
    family: str
    devices: int
    optimizer: str
    ranked: list = field(default_factory=list)  # feasible, best first
    infeasible: list = field(default_factory=list)  # RankedPlan, reasoned
    hand_dims: dict | None = None
    # param-footprint pricing the search scored under ("as-traced" or a
    # DTYPE_BYTES name): recorded in the plan manifest - a plan searched
    # at int8 pricing is not comparable to a bf16 one
    precision: str = "as-traced"

    @property
    def chosen(self) -> RankedPlan | None:
        return self.ranked[0] if self.ranked else None

    def matches_hand_config(self) -> bool | None:
        if self.chosen is None or self.hand_dims is None:
            return None
        return (
            self.chosen.dims == self.hand_dims
            and self.chosen.optimizer == self.optimizer
        )

    def explain(self, *, top_k: int | None = None) -> str:
        """The ranked table + per-term why breakdown for the winner."""
        lines = [
            f"{self.config}: searched {len(self.ranked) + len(self.infeasible)}"
            f" plan(s) over {self.devices} device(s), "
            f"{len(self.ranked)} feasible"
        ]
        show = self.ranked if top_k is None else self.ranked[:top_k]
        for i, p in enumerate(show):
            marker = " <- chosen" if i == 0 else ""
            hand = (
                " (hand-written mesh)"
                if self.hand_dims is not None and p.dims == self.hand_dims
                else ""
            )
            lines.append(
                f"  #{i + 1} {p.label:<26} score {p.score:>14,.1f}"
                f"{hand}{marker}"
            )
        for p in self.infeasible:
            lines.append(
                f"   - {p.label:<26} INFEASIBLE: {p.infeasible_reason}"
            )
        if self.chosen is not None:
            lines.append("why the winner:")
            lines.extend(
                "  " + ln for ln in self.chosen.breakdown.why().splitlines()
            )
        return "\n".join(lines)


def _first_line(exc: Exception) -> str:
    return f"{type(exc).__name__}: {exc}".splitlines()[0][:300]


def search_plans(
    family: str,
    *,
    cfg,
    devices: int,
    batch: int,
    seq_len: int,
    optimizer: str,
    kwargs: dict | None = None,
    optimizers: tuple | None = None,
    weights: CostWeights | None = None,
    config: str = "adhoc",
    hand_dims: dict | None = None,
) -> SearchResult:
    """Enumerate -> build -> trace -> score every candidate plan for one
    model scenario; returns the deterministic ranking (score, then label).

    ``optimizers`` widens the optimizer-layout dimension of the search
    (e.g. ("sgd", "zero") scores the ZeRO weight-update sharding of
    arXiv 2004.13336 against the replicated update); default is just the
    scenario's own optimizer, which keeps the checked-in plans stable.
    """
    result = SearchResult(
        config=config, family=family, devices=devices,
        optimizer=optimizer, hand_dims=hand_dims,
        precision=(weights.param_precision if weights is not None
                   and weights.param_precision else "as-traced"),
    )
    dims_list = (
        lm_mesh_candidates(devices) if family == "lm"
        else pp_mesh_candidates(devices)
    )
    for dims in dims_list:
        for opt in optimizers or (optimizer,):
            label = _plan_label(family, dims, opt)
            plan = RankedPlan(
                label=label, family=family, dims=dict(dims), optimizer=opt
            )
            try:
                program = build_candidate_program(
                    family, dims, cfg=cfg, batch=batch, seq_len=seq_len,
                    optimizer=opt, kwargs=kwargs, name=label,
                )
                facts = collect_trace(program.make_jaxpr())
                plan.breakdown = score_program(
                    program, facts, weights, plan=label
                )
            except Exception as e:  # pruned: divisibility, axis rules, ...
                plan.infeasible_reason = _first_line(e)
            if plan.feasible:
                result.ranked.append(plan)
            else:
                if plan.breakdown is not None:
                    plan.infeasible_reason = (
                        plan.breakdown.infeasible_reason
                    )
                result.infeasible.append(plan)
    result.ranked.sort(key=lambda p: (p.score, p.label))
    return result


def search_config(
    name: str,
    *,
    devices: int | None = None,
    weights: CostWeights | None = None,
    optimizers: tuple | None = None,
) -> SearchResult:
    """The canonical-config entry: search the scenario behind one
    shardlint config (same trace model, same step kwargs) over every
    mesh factorization of its device count."""
    try:
        bp = BLUEPRINTS[name]
    except KeyError:
        raise KeyError(
            f"unknown autoshard config {name!r}; searchable configs: "
            f"{', '.join(searchable_config_names())}"
        ) from None
    if bp["family"] not in ("lm", "pp"):
        raise ValueError(
            f"config {name!r} (family {bp['family']!r}) has no mesh "
            "factorization to search; searchable configs: "
            f"{', '.join(searchable_config_names())}"
        )
    if bp["family"] == "lm":
        hand = {"dp": bp["dp"], "sp": bp["sp"], "tp": bp["tp"]}
        n = bp["dp"] * bp["sp"] * bp["tp"]
    else:
        hand = {"dp": bp["dp"], "pp": bp["pp"]}
        n = bp["dp"] * bp["pp"]
    return search_plans(
        bp["family"], cfg=_trace_cfg(**bp.get("cfg_kwargs", {})),
        devices=devices or n,
        batch=TRACE_BATCH, seq_len=TRACE_SEQ, optimizer=bp["optimizer"],
        kwargs=bp["kwargs"], optimizers=optimizers, weights=weights,
        config=name, hand_dims=hand if devices in (None, n) else None,
    )


# --------------------------------------------------------- plan manifests


def build_plan_doc(result: SearchResult) -> dict:
    """The checked-in plan manifest for one search (analysis/plans/)."""
    import jax

    chosen = result.chosen
    if chosen is None:
        raise ValueError(
            f"{result.config}: no feasible plan to pin - "
            + "; ".join(
                f"{p.label}: {p.infeasible_reason}" for p in result.infeasible
            )
        )
    bd = chosen.breakdown
    return {
        "schema": PLAN_SCHEMA,
        "config": result.config,
        "jax_version": jax.__version__,
        "trace_mode": compat.trace_mode(),
        "family": result.family,
        "devices": result.devices,
        "hand_dims": result.hand_dims,
        "matches_hand_config": result.matches_hand_config(),
        "precision": result.precision,
        "chosen": {
            "plan": chosen.label,
            "dims": chosen.dims,
            "optimizer": chosen.optimizer,
            "score": round(float(bd.score), 3),
            "collective_bytes": int(bd.collective_bytes),
            "wire_bytes": round(float(bd.wire_bytes), 3),
            "untraced_grad_sync_bytes": round(
                float(bd.untraced_grad_sync_bytes), 3
            ),
            "peak_state_bytes": int(bd.peak_state_bytes),
        },
        "ranking": [
            {
                "plan": p.label,
                "score": round(float(p.score), 3),
                "collective_bytes": int(p.breakdown.collective_bytes),
            }
            for p in result.ranked[:5]
        ],
        "infeasible": {
            p.label: p.infeasible_reason for p in result.infeasible
        },
    }


def save_plan(doc: dict, name: str, plan_dir: str | None = None) -> str:
    path = plan_path(name, plan_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True, allow_nan=False)
        f.write("\n")
    return path


def load_plan(name: str, plan_dir: str | None = None) -> dict:
    path = plan_path(name, plan_dir)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no plan manifest for config {name!r} at {path} - generate "
            f"one with: python tools/autoshard.py --model {name} "
            "--write-manifest"
        )
    with open(path) as f:
        return json.load(f)


def diff_plans(expected: dict, result: SearchResult) -> list:
    """Human-actionable drift between a checked-in plan and a fresh
    search (empty == conforming). Environment mismatches short-circuit
    with a regenerate instruction, like collective manifests."""
    import jax

    actual_env = {"jax_version": jax.__version__,
                  "trace_mode": compat.trace_mode()}
    for key in ("jax_version", "trace_mode"):
        if expected.get(key) != actual_env[key]:
            return [
                f"plan for {expected.get('config')!r} was written under "
                f"{key}={expected.get(key)!r} but this run has "
                f"{key}={actual_env[key]!r}: traced programs are not "
                "comparable across jax generations - regenerate with "
                "--write-manifest (docs/STATIC_ANALYSIS.md)"
            ]
    msgs = []
    if (expected.get("precision") or "as-traced") != result.precision:
        return [
            f"plan for {expected.get('config')!r} was searched under "
            f"precision={expected.get('precision') or 'as-traced'!r} but "
            f"this run priced {result.precision!r} - quantized and "
            "full-precision footprints rank differently; regenerate or "
            "drop --precision"
        ]
    if expected.get("devices") != result.devices:
        return [
            f"device count changed: plan searched {expected.get('devices')}"
            f", this run searched {result.devices} - regenerate or pass "
            "--devices"
        ]
    chosen = result.chosen
    exp = expected.get("chosen") or {}
    if chosen is None:
        return [
            "no feasible plan found, but the checked-in manifest chose "
            f"{exp.get('plan')!r}"
        ]
    if exp.get("dims") != chosen.dims or exp.get("optimizer") != chosen.optimizer:
        msgs.append(
            f"top-ranked plan changed: manifest chose {exp.get('plan')!r}, "
            f"the search now ranks {chosen.label!r} first - review and "
            "either fix the regression or bless the new plan with "
            "--write-manifest"
        )
    elif exp.get("collective_bytes") != chosen.breakdown.collective_bytes:
        msgs.append(
            f"chosen plan's collective bytes changed: "
            f"{exp.get('collective_bytes'):,} -> "
            f"{chosen.breakdown.collective_bytes:,} per step (the plan "
            "still wins, but its traced program drifted - shardlint "
            "--check should name the site; regenerate both manifests "
            "together)"
        )
    return msgs


# ------------------------------------------------------------ the driver


def run_autoshard(
    names=None,
    *,
    mode: str = "rank",
    plan_dir: str | None = None,
    devices: int | None = None,
    explain: bool = False,
    optimizers: tuple | None = None,
    weights: CostWeights | None = None,
    verbose: bool = True,
):
    """Search configs; mode: 'rank' (print the ranking), 'write' (pin the
    winner as a plan manifest), 'check' (diff the fresh winner against
    the checked-in plan). Returns (exit_code, report) - 0 conforming,
    1 drift/missing plan, 2 a search failed - mirroring run_shardlint."""
    if mode not in ("rank", "write", "check"):
        raise ValueError(f"mode must be rank/write/check, got {mode!r}")
    names = list(names) if names else searchable_config_names()
    lines = []
    worst = 0

    def fail(rc):
        nonlocal worst
        worst = max(worst, rc)

    for name in names:
        try:
            result = search_config(
                name, devices=devices, optimizers=optimizers,
                weights=weights,
            )
        except Exception as e:
            fail(2)
            lines.append(f"{name}: SEARCH FAILED - {_first_line(e)}")
            continue
        chosen = result.chosen
        if chosen is None:
            fail(2)
            lines.append(
                f"{name}: no feasible plan over {result.devices} device(s)"
            )
            for p in result.infeasible:
                lines.append(f"    {p.label}: {p.infeasible_reason}")
            continue
        hand = result.matches_hand_config()
        hand_note = (
            "matches the hand-written config" if hand
            else "DIFFERS from the hand-written config" if hand is False
            else "no hand-written baseline"
        )
        lines.append(
            f"{name}: chose {chosen.label} "
            f"(score {chosen.score:,.1f}; {len(result.ranked)} feasible / "
            f"{len(result.infeasible)} pruned; {hand_note})"
        )
        if explain or (verbose and mode == "rank"):
            lines.extend("    " + ln for ln in result.explain().splitlines())
        if mode == "write":
            path = save_plan(build_plan_doc(result), name, plan_dir)
            lines.append(f"    wrote {path}")
        elif mode == "check":
            try:
                expected = load_plan(name, plan_dir)
            except FileNotFoundError as e:
                fail(1)
                lines.append(f"    {e}")
                continue
            diffs = diff_plans(expected, result)
            if diffs:
                fail(1)
                lines.append(f"    {name}: PLAN MISMATCH:")
                lines.extend(f"      - {d}" for d in diffs)
            else:
                lines.append(f"    plan conforms ({name}.json)")
    status = {0: "OK", 1: "FAIL", 2: "SEARCH ERROR"}[worst]
    lines.append(f"autoshard: {len(names)} config(s), {status}")
    return worst, "\n".join(lines)


# ----------------------------------------- the CNN engine's trivial plan


def auto_nb_proc(batch_size: int, device_count: int) -> int:
    """The CNN engine's one free sharding choice: the batch-axis worker
    count. The largest divisor of the global batch that fits the device
    count - every worker gets an identical integer share (the engine's
    divisibility contract), on as many devices as possible."""
    if batch_size < 1 or device_count < 1:
        raise ValueError(
            f"batch_size and device_count must be >= 1, got "
            f"{batch_size}/{device_count}"
        )
    for n in range(min(batch_size, device_count), 0, -1):
        if batch_size % n == 0:
            return n
    return 1
