"""Static cost model: score a sharding plan from its abstract trace.

Input: a `StepProgram` (train/program.py) and the `TraceFacts` the
shardlint tracer (trace.py) computed for it - collective op/axes/bytes
with static multiplicity, scan-carry footprints, donation coverage. All
of it exists WITHOUT executing anything, which is what makes the
autoshard search (autoshard.py) cheap: scoring a candidate costs one
``jax.make_jaxpr`` trace, never a compile or a device.

The score (lower is better) combines four terms:

1. **Collective wire bytes.** Each static site's logical payload bytes
   (trace.py byte convention: input avals, except all_gather which counts
   its output) are converted to per-device wire bytes with the standard
   ring factors over the site's axis group size n = prod(mesh[axis]):
   psum (ring all-reduce) 2(n-1)/n, all_gather / reduce_scatter /
   all_to_all (n-1)/n, ppermute 1. Dynamic (while-loop) sites have no
   static trip count; they are surfaced in the breakdown but excluded
   from the score, matching the manifest convention.
2. **Per-device peak state bytes** vs an HBM budget: params + optimizer
   state sharded per the plan's PartitionSpecs (each leaf's bytes divided
   by the product of its spec's axis sizes) + the largest scan carry.
   Over budget = infeasible (the search prunes it); under budget a small
   pressure term still prefers leaner layouts.
3. **Donation coverage.** Un-donated state doubles its peak bytes during
   the step; the undonated fraction of state bytes is charged at
   ``donation_weight``.
4. **Replication-leak penalty.** A ZeRO overlap plan whose in-scan
   gradient carry is not O(D/dp) (lint.py's leak threshold: carry >= D/2)
   is charged the full leaked bytes - such a plan must never outrank a
   correctly sharded one.

On jax builds that trace through the pre-vma compat path
(``compat.trace_mode() == "compat"``), the typed-autodiff gradient psums
of `grad_sync="end"` steps are INVISIBLE in the trace. The model adds
them analytically (replicated param-leaf bytes, psum ring factor over
the sync axes) so end-sync data parallelism is never scored as free; on
native traces the same psums appear in `TraceFacts` and the analytic
term stays zero - never both.

`predicted_collective_bytes` (the logical per-step total) is by
construction EQUAL to the shardlint manifest's ``total_collective_bytes``
for the same config - one `TraceFacts` source, pinned by test.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# ------------------------------------------------------- bytes per dtype
#
# The low-precision pricing table (ROADMAP item 3's closing clause): the
# HBM-feasibility gate, `step_seconds`, and the serving KV-capacity math
# all consult it, so autoshard can trade precision for parallelism (an
# int8 plan that fits where a bf16 plan did not) and the serving stack
# reports occupancy in the bytes it actually allocates. Quantized
# formats carry per-block f32 scales - `quantized_bytes` charges them,
# so a "free" 4x never appears in a feasibility decision.

DTYPE_BYTES = {
    "f32": 4, "float32": 4, "fp32": 4,
    "bf16": 2, "bfloat16": 2, "f16": 2, "float16": 2,
    "int8": 1, "fp8": 1, "fp8-e4m3": 1, "float8_e4m3fn": 1,
}
# formats that need a dequantization scale riding along
QUANTIZED_DTYPES = ("int8", "fp8", "fp8-e4m3", "float8_e4m3fn")
SCALE_BYTES = 4  # one f32 scale per quantization block


def dtype_bytes(name: str) -> int:
    try:
        return DTYPE_BYTES[str(name)]
    except KeyError:
        raise ValueError(
            f"unknown dtype name {name!r}; known: "
            f"{', '.join(sorted(DTYPE_BYTES))}"
        ) from None


def quantized_bytes(n_elements: int, dtype: str, *,
                    quant_block: int = 64) -> int:
    """Storage bytes of ``n_elements`` in ``dtype`` INCLUDING the per-
    block f32 scales quantized formats carry (one scale per
    ``quant_block`` elements) - the honest footprint the HBM gate and
    the KV-capacity math price."""
    total = n_elements * dtype_bytes(dtype)
    if str(dtype) in QUANTIZED_DTYPES:
        total += -(-n_elements // max(quant_block, 1)) * SCALE_BYTES
    return total


def kv_block_bytes(n_layers: int, n_heads: int, head_dim: int,
                   block_size: int, dtype: str = "bf16") -> int:
    """Device bytes of ONE paged-KV block (serve/kv_cache.py): K + V
    slabs for every layer, plus - for quantized dtypes - the
    per-(block, head) f32 scale pair each layer stores. The serving
    capacity multiplier is exactly bf16's figure over int8's."""
    elems = 2 * n_layers * block_size * n_heads * head_dim  # K and V
    total = elems * dtype_bytes(dtype)
    if str(dtype) in QUANTIZED_DTYPES:
        total += 2 * n_layers * n_heads * SCALE_BYTES
    return total


def kv_capacity_sequences(usable_blocks: int, block_size: int,
                          seq_len: int) -> int:
    """Concurrent sequences of ``seq_len`` tokens a pool of
    ``usable_blocks`` holds - the *effective* capacity figure the
    /metrics gauge and tools/live_top.py report instead of a raw block
    count."""
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    blocks_per_seq = -(-seq_len // block_size)
    return usable_blocks // blocks_per_seq


@dataclass(frozen=True)
class CostWeights:
    """Weights/budget for `score_program`. Defaults favour wire bytes as
    the primary signal (the quantity manifests already pin) with memory
    as a feasibility gate plus a mild pressure term."""

    wire_weight: float = 1.0  # per wire byte moved per step
    mem_weight: float = 0.01  # per peak state byte per device
    donation_weight: float = 0.5  # per un-donated state byte
    leak_weight: float = 4.0  # per leaked (unsharded ZeRO carry) byte
    hbm_bytes: int = 16 * 2**30  # per-device budget (v5e-class default)
    # price PARAM floating leaves as if stored in this dtype ("int8" /
    # "fp8" / "bf16"; None = as traced): the quantized-footprint knob
    # that lets the HBM-feasibility gate trade precision for parallelism
    # - an int8 plan fits meshes a bf16 plan prunes (tools/autoshard.py
    # --precision). Optimizer state is NEVER repriced (master weights /
    # moments stay wide; quantizing them is a different algorithm, not
    # a storage choice), and quantized formats are charged their
    # per-block scale overhead (`quantized_bytes`).
    param_precision: str | None = None
    quant_block: int = 64  # elements per quantization scale


# ring wire factor per logical payload byte, by op, for axis group size n
def wire_factor(op: str, n: int) -> float:
    if n <= 1:
        return 0.0
    if op == "psum":
        return 2.0 * (n - 1) / n
    if op in ("all_gather", "reduce_scatter", "all_to_all"):
        return (n - 1) / n
    if op == "ppermute":
        return 1.0
    return 1.0


@dataclass
class CostBreakdown:
    """One plan's score with every term exposed (the --explain payload)."""

    plan: str
    mesh: dict
    feasible: bool = True
    infeasible_reason: str = ""
    # term 1: collectives
    collective_bytes: int = 0  # logical, static sites == manifest total
    dynamic_collective_bytes: int = 0  # per while-iteration, unscored
    wire_bytes: float = 0.0  # ring-weighted, traced sites
    wire_bytes_by_axes: dict = field(default_factory=dict)
    untraced_grad_sync_bytes: float = 0.0  # analytic compat-trace term
    # term 2: memory
    param_bytes_per_device: int = 0
    opt_bytes_per_device: int = 0
    scan_carry_bytes: int = 0
    peak_state_bytes: int = 0
    hbm_bytes: int = 0
    param_precision: str = ""  # "" = as traced; else the priced dtype
    # term 3: donation
    state_bytes_total: int = 0
    undonated_state_bytes: int = 0
    # term 4: leak
    leaked_carry_bytes: int = 0
    score: float = float("inf")

    def why(self) -> str:
        """Human-readable breakdown, one line per term."""
        if not self.feasible:
            return (
                f"{self.plan}: INFEASIBLE - {self.infeasible_reason}"
            )
        lines = [
            f"{self.plan}: score {self.score:,.1f}",
            f"  wire bytes/step      {self.wire_bytes:>14,.1f}  "
            f"(logical {self.collective_bytes:,} B over "
            + (
                ", ".join(
                    f"{'+'.join(a) or 'local'}: {b:,.1f}"
                    for a, b in sorted(self.wire_bytes_by_axes.items())
                )
                or "no collectives"
            )
            + ")",
        ]
        if self.untraced_grad_sync_bytes:
            lines.append(
                f"  + grad-sync (analytic) {self.untraced_grad_sync_bytes:>12,.1f}  "
                "(end-sync psums invisible to the compat trace)"
            )
        if self.dynamic_collective_bytes:
            lines.append(
                f"  dynamic bytes/iter   {self.dynamic_collective_bytes:>14,}  "
                "(while-loop sites, excluded from the score)"
            )
        lines.append(
            f"  peak state B/device  {self.peak_state_bytes:>14,}  "
            f"(params {self.param_bytes_per_device:,}"
            + (f" @{self.param_precision}" if self.param_precision else "")
            + f" + opt {self.opt_bytes_per_device:,} + carry "
            f"{self.scan_carry_bytes:,}; budget {self.hbm_bytes:,})"
        )
        if self.undonated_state_bytes:
            lines.append(
                f"  un-donated state B   {self.undonated_state_bytes:>14,}  "
                "(double-buffered during the step)"
            )
        if self.leaked_carry_bytes:
            lines.append(
                f"  ZeRO leak penalty B  {self.leaked_carry_bytes:>14,}  "
                "(in-scan carry not O(D/dp))"
            )
        return "\n".join(lines)


def sharded_leaf_bytes(avals, specs, mesh_axes, *,
                       precision: str | None = None,
                       quant_block: int = 64) -> int:
    """Per-device bytes of an abstract state tree under a spec tree: each
    leaf's bytes divided by the product of its spec's axis sizes (the
    spec may be a pytree prefix, shard_map's broadcast rule).

    ``precision`` reprices FLOATING leaves as if stored in that dtype
    (per-block scale overhead included) - the quantized-footprint view
    of the same tree; integer leaves (token buffers, indices) keep
    their traced bytes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec

    def is_spec(s):
        return isinstance(s, PartitionSpec)

    spec_leaves, treedef = jax.tree_util.tree_flatten(specs, is_leaf=is_spec)
    aval_groups = treedef.flatten_up_to(avals)
    total = 0
    for spec, group in zip(spec_leaves, aval_groups):
        shards = 1
        for entry in tuple(spec):
            if entry is None:
                continue
            for a in (entry,) if isinstance(entry, str) else tuple(entry):
                shards *= int(mesh_axes.get(a, 1))
        for leaf in jax.tree_util.tree_leaves(group):
            if not hasattr(leaf, "shape"):
                continue
            n = int(np.prod(leaf.shape, dtype=np.int64))
            if precision is not None and jnp.issubdtype(
                leaf.dtype, jnp.floating
            ):
                # ceil-shard the ELEMENTS, then price at the target
                # dtype (+ scale overhead): padding is real memory
                total += quantized_bytes(
                    -(-n // shards), precision, quant_block=quant_block
                )
            else:
                nbytes = n * np.dtype(leaf.dtype).itemsize
                total += -(-nbytes // shards)
    return total


def replicated_param_bytes(program) -> int:
    """Bytes of param leaves whose spec is fully replicated (no mesh axis
    named) - the leaves whose end-sync gradients psum over the sync axes."""
    import jax
    from jax.sharding import PartitionSpec

    specs = (program.specs or {}).get("params")
    if specs is None or not program.abstract_args:
        return 0

    def is_spec(s):
        return isinstance(s, PartitionSpec)

    spec_leaves, treedef = jax.tree_util.tree_flatten(specs, is_leaf=is_spec)
    aval_groups = treedef.flatten_up_to(program.abstract_args[0])
    total = 0
    for spec, group in zip(spec_leaves, aval_groups):
        if any(e is not None for e in tuple(spec)):
            continue
        for leaf in jax.tree_util.tree_leaves(group):
            if hasattr(leaf, "shape"):
                total += int(
                    np.prod(leaf.shape, dtype=np.int64)
                ) * np.dtype(leaf.dtype).itemsize
    return total


def untraced_grad_sync_wire_bytes(program, facts) -> float:
    """Analytic wire bytes of the end-sync gradient psums the COMPAT trace
    cannot see (pre-vma jax traces no typed-autodiff psums). Zero on
    native traces (the psums are in `facts`), zero under overlap sync
    (its collectives are explicit and traced), zero when no sync axis has
    size > 1."""
    from .. import compat

    if compat.trace_mode() != "compat":
        return 0.0
    meta = program.meta or {}
    if meta.get("family") not in ("lm", "pp"):
        return 0.0
    if meta.get("grad_sync") == "overlap" and int(meta.get("accum_steps", 1)) > 1:
        return 0.0
    mesh_axes = dict(program.mesh.shape)
    sync_axes = [
        a for a in (meta.get("sync_axes") or []) if mesh_axes.get(a, 1) > 1
    ]
    if not sync_axes:
        return 0.0
    n = 1
    for a in sync_axes:
        n *= int(mesh_axes[a])
    rep = replicated_param_bytes(program)
    if str(meta.get("optimizer", "")).startswith("zero"):
        # ZeRO end-sync reduces with reduce_scatter + all_gather instead
        # of a full all-reduce; same (n-1)/n each way = same 2(n-1)/n
        # total, so the psum factor is the right analytic stand-in
        pass
    return rep * wire_factor("psum", n)


def score_program(program, facts, weights: CostWeights | None = None,
                  plan: str | None = None) -> CostBreakdown:
    """Score one traced plan. Never raises on a scoreable program; memory
    over budget marks the breakdown infeasible (score stays +inf)."""
    w = weights or CostWeights()
    mesh_axes = {str(k): int(v) for k, v in program.mesh.shape.items()}
    bd = CostBreakdown(
        plan=plan or program.name, mesh=mesh_axes,
        hbm_bytes=int(w.hbm_bytes),
        param_precision=w.param_precision or "",
    )

    # --- term 1: collectives -------------------------------------------
    bd.collective_bytes = facts.total_collective_bytes()
    bd.dynamic_collective_bytes = facts.dynamic_collective_bytes_per_iter()
    by_axes = {}
    for c in facts.collectives:
        if c.dynamic:
            continue
        n = 1
        for a in c.axes:
            n *= int(mesh_axes.get(a, 1))
        wb = c.total_bytes * wire_factor(c.op, n)
        bd.wire_bytes += wb
        by_axes[c.axes] = by_axes.get(c.axes, 0.0) + wb
    bd.wire_bytes_by_axes = by_axes
    bd.untraced_grad_sync_bytes = untraced_grad_sync_wire_bytes(
        program, facts
    )

    # --- term 2: memory -------------------------------------------------
    args = program.abstract_args
    specs = program.specs or {}
    if args and "params" in specs:
        bd.param_bytes_per_device = sharded_leaf_bytes(
            args[0], specs["params"], mesh_axes,
            precision=w.param_precision, quant_block=w.quant_block,
        )
    if len(args) > 1 and "opt" in specs:
        bd.opt_bytes_per_device = sharded_leaf_bytes(
            args[1], specs["opt"], mesh_axes
        )
    bd.scan_carry_bytes = int(facts.scan_carry_max_bytes)
    bd.peak_state_bytes = (
        bd.param_bytes_per_device + bd.opt_bytes_per_device
        + bd.scan_carry_bytes
    )
    if bd.peak_state_bytes > w.hbm_bytes:
        bd.feasible = False
        bd.infeasible_reason = (
            f"peak state {bd.peak_state_bytes:,} B/device exceeds the HBM "
            f"budget {int(w.hbm_bytes):,} B (params "
            f"{bd.param_bytes_per_device:,} + optimizer "
            f"{bd.opt_bytes_per_device:,} + scan carry "
            f"{bd.scan_carry_bytes:,})"
        )
        return bd

    # --- term 3: donation ----------------------------------------------
    bd.state_bytes_total = bd.param_bytes_per_device + bd.opt_bytes_per_device
    donated = facts.donated_invars
    if donated is not None and program.donate:
        counts = program.arg_leaf_counts()
        if sum(counts) == len(donated):
            offsets = [0]
            for cnt in counts:
                offsets.append(offsets[-1] + cnt)
            state_bytes = [bd.param_bytes_per_device, bd.opt_bytes_per_device]
            for argnum in program.donate:
                if argnum >= len(counts) or argnum >= len(state_bytes):
                    continue
                flags = donated[offsets[argnum]:offsets[argnum + 1]]
                if flags and not all(flags):
                    frac = 1.0 - sum(flags) / len(flags)
                    bd.undonated_state_bytes += int(
                        state_bytes[argnum] * frac
                    )
    elif donated is None and program.donate:
        # no jit boundary found: charge the full state conservatively
        bd.undonated_state_bytes = bd.state_bytes_total

    # --- term 4: ZeRO replication leak ----------------------------------
    meta = program.meta or {}
    if (
        str(meta.get("optimizer", "")).startswith("zero")
        and meta.get("grad_sync") == "overlap"
        and int(meta.get("accum_steps", 1)) > 1
    ):
        dp = int(meta.get("dp", 1))
        d_bytes = program.param_bytes()
        carry = facts.reduce_scatter_carry_bytes
        if carry is None:
            bd.leaked_carry_bytes = d_bytes  # schedule not running at all
        elif dp > 1 and carry >= d_bytes // 2:
            bd.leaked_carry_bytes = carry - d_bytes // dp

    bd.score = (
        w.wire_weight * (bd.wire_bytes + bd.untraced_grad_sync_bytes)
        + w.mem_weight * bd.peak_state_bytes
        + w.donation_weight * bd.undonated_state_bytes
        + w.leak_weight * bd.leaked_carry_bytes
    )
    return bd


# ------------------------------------------------------ per-step seconds
#
# The score above RANKS plans; the fleet digital twin
# (analysis/fleetsim.py) needs SECONDS - a predicted steady-step time it
# can multiply into goodput under a failure process. `step_seconds`
# converts the same byte/flop terms into a first-order roofline estimate:
# compute and HBM weight-streaming overlap (the max rules), collective
# wire time is charged serially on top (the conservative bound for
# unoverlapped end-sync; the overlap schedule hides part of it, which the
# estimate deliberately does not credit). Pure arithmetic over a
# `CostBreakdown` OR a checked-in plan manifest's "chosen" dict - no jax,
# so a supervisor-side tool can price a plan without a runtime.


@dataclass(frozen=True)
class HardwareModel:
    """Nominal per-chip rates for step-time pricing. The defaults are
    v5e-class datasheet numbers; calibrate against a measured record
    (the twin prefers the measured step-time distribution whenever one
    exists - this model is for fleets/plans never executed)."""

    name: str = "tpu-v5e"
    flops_per_s: float = 197e12  # bf16 peak, per chip
    hbm_bytes_per_s: float = 819e9  # HBM bandwidth, per chip
    ici_bytes_per_s: float = 45e9  # per-link ICI wire bandwidth
    step_overhead_s: float = 50e-6  # dispatch/launch floor per step


# named hardware presets for the CLIs (tools/fleetsim.py --hw)
HARDWARE_MODELS = {
    "tpu-v5e": HardwareModel(),
    "tpu-v4": HardwareModel(
        name="tpu-v4", flops_per_s=275e12, hbm_bytes_per_s=1228e9,
        ici_bytes_per_s=100e9,
    ),
    "cpu-host": HardwareModel(
        name="cpu-host", flops_per_s=2e11, hbm_bytes_per_s=40e9,
        ici_bytes_per_s=10e9, step_overhead_s=1e-3,
    ),
}


@dataclass
class StepTime:
    """One plan's predicted steady-step seconds, every term exposed."""

    step_s: float
    compute_s: float
    memory_s: float
    comm_s: float
    overhead_s: float
    bound: str  # "compute" | "memory" | "comm"
    flops_per_step: float
    hw: str

    def why(self) -> str:
        return (
            f"step {self.step_s * 1e3:,.3f} ms on {self.hw} "
            f"({self.bound}-bound: compute {self.compute_s * 1e3:,.3f} + "
            f"hbm {self.memory_s * 1e3:,.3f} [max] + wire "
            f"{self.comm_s * 1e3:,.3f} + overhead "
            f"{self.overhead_s * 1e3:,.3f} ms)"
        )


def dense_step_flops(param_count: float, tokens_per_step: float) -> float:
    """First-order dense-transformer training flops per step: 6 x params
    x tokens (fwd 2PT + bwd 4PT, the standard accounting)."""
    return 6.0 * float(param_count) * float(tokens_per_step)


def serve_tick_seconds(
    bucket, hw: HardwareModel | None = None
) -> StepTime:
    """Predicted seconds of ONE serve bucket call (decode / chunked
    prefill / spec verify) from its traced facts - the serving analogue
    of `step_seconds`, consumed by the servelint capacity planner
    (analysis/serve_trace.py) and the fleet twin.

    ``bucket`` is any mapping exposing ``flops`` and ``hbm_bytes`` - a
    serve manifest's per-bucket doc qualifies, so a supervisor-side
    tool can price a config it never compiled. Model: compute and HBM
    streaming overlap (take the max - the weights stream while the MXU
    works), plus the dispatch floor; serve programs are single-device,
    so there is no wire term."""
    hw = hw or HardwareModel()

    def get(key):
        if isinstance(bucket, dict):
            return float(bucket.get(key) or 0.0)
        return float(getattr(bucket, key, 0.0) or 0.0)

    compute_s = get("flops") / hw.flops_per_s
    memory_s = get("hbm_bytes") / hw.hbm_bytes_per_s
    return StepTime(
        step_s=max(compute_s, memory_s) + hw.step_overhead_s,
        compute_s=compute_s,
        memory_s=memory_s,
        comm_s=0.0,
        overhead_s=hw.step_overhead_s,
        bound="compute" if compute_s >= memory_s else "memory",
        flops_per_step=get("flops"),
        hw=hw.name,
    )


def _full_bucket(manifest: dict, family: str) -> dict | None:
    """The largest (last-sorted) bucket doc of one family, or None."""
    docs = [
        b for b in manifest.get("buckets", []) if b.get("family") == family
    ]
    if not docs:
        return None
    return max(docs, key=lambda b: tuple(b["bucket"]))


def serve_capacity(manifest: dict, hw: HardwareModel | None = None) -> dict:
    """Static capacity curves of one serve config from its servelint
    manifest (analysis/serve_trace.py) - the planner view ROADMAP item
    1 asks for, consumable by analysis/fleetsim.py and the autoscaler
    sizing logic (`replicas_for_target`):

    - steady-state decode ``tokens_per_s`` at the FULL decode bucket
      (every slot busy - the per-replica throughput ceiling);
    - static prefill TTFT per pow2 prompt length: ceil(P / C) chunked
      prefill calls at the full chunk bucket plus the first decode tick
      (without chunked prefill, P token-at-a-time decode ticks);
    - concurrent-sequence KV capacity per prompt+generation length
      (`kv_capacity_sequences` over the manifest's pool geometry).

    Pure arithmetic over pinned facts - no jax, no engine."""
    hw = hw or HardwareModel()
    eng = manifest.get("engine", {})
    kv = manifest.get("kv", {})
    out: dict = {"hw": hw.name}

    dec = _full_bucket(manifest, "decode")
    if dec is not None:
        tick = serve_tick_seconds(dec, hw)
        B = int(dec["bucket"][0])
        out["decode"] = {
            "bucket": list(dec["bucket"]),
            "tick_s": tick.step_s,
            "bound": tick.bound,
            "tokens_per_s": B / tick.step_s,
        }

    pre = _full_bucket(manifest, "prefill")
    chunk = int(pre["bucket"][0]) if pre is not None else 0
    if pre is not None:
        ptick = serve_tick_seconds(pre, hw)
        out["prefill"] = {
            "bucket": list(pre["bucket"]),
            "tick_s": ptick.step_s,
            "tokens_per_s": chunk / ptick.step_s,
        }

    max_seq = int(eng.get("max_seq_len") or 0)
    block_size = int(eng.get("block_size") or 1)
    usable = int(kv.get("usable_blocks") or 0)
    ttft: dict = {}
    kv_cap: dict = {}
    if dec is not None and max_seq:
        dtick = serve_tick_seconds(dec, hw).step_s
        p = 1
        lens = []
        while p < max_seq:
            lens.append(p)
            p *= 2
        lens.append(max_seq)
        for P in lens:
            if pre is not None and chunk:
                n_calls = -(-P // chunk)
                ttft[str(P)] = n_calls * ptick.step_s + dtick
            else:
                ttft[str(P)] = P * dtick + dtick
            kv_cap[str(P)] = kv_capacity_sequences(usable, block_size, P)
    out["ttft_s"] = ttft
    out["kv_capacity_sequences"] = kv_cap
    return out


def replicas_for_target(
    capacity: dict,
    *,
    target_rps: float,
    mean_new_tokens: float,
    prompt_len: int = 0,
    target_ttft_s: float | None = None,
) -> dict:
    """Replica count for a target request rate - the capacity-planner
    arithmetic the PR 18 autoscaler's ``min_replicas`` should be seeded
    from (serve/fleet.py autoscale_decision enforces it at runtime;
    this answers it BEFORE provisioning).

    ``capacity`` is `serve_capacity`'s output (or a manifest's pinned
    ``capacity[hw]`` block). The demand is ``target_rps *
    mean_new_tokens`` decode tokens/s against the per-replica ceiling;
    a ``target_ttft_s`` is checked against the STATIC prefill floor at
    ``prompt_len`` - a floor above the target is infeasible at any
    replica count (queueing only adds to it), which the planner reports
    instead of scaling forever."""
    dec = capacity.get("decode") or {}
    per_replica = float(dec.get("tokens_per_s") or 0.0)
    if per_replica <= 0:
        raise ValueError(
            "capacity has no decode tokens_per_s figure - pass "
            "serve_capacity() output or a manifest capacity block"
        )
    import math

    demand = float(target_rps) * float(mean_new_tokens)
    replicas = max(1, math.ceil(demand / per_replica))
    out = {
        "replicas": int(replicas),
        "demand_tokens_per_s": demand,
        "per_replica_tokens_per_s": per_replica,
        "utilization_at_n": demand / (replicas * per_replica),
        "feasible": True,
        # provenance: this figure ignores queueing - scripts must not
        # confuse it with the serve twin's dynamic answer
        # (analysis/fleetsim.py replicas_for_dynamic, which is >= this)
        "static_only": True,
        "why": (
            f"{demand:,.0f} tok/s demand / {per_replica:,.0f} tok/s "
            f"per replica -> {replicas} replica(s)"
        ),
    }
    if target_ttft_s is not None and prompt_len:
        curve = capacity.get("ttft_s") or {}
        floor = None
        for key in sorted(curve, key=int):
            if int(key) >= int(prompt_len):
                floor = float(curve[key])
                break
        if floor is None and curve:
            floor = float(curve[max(curve, key=int)])
        out["ttft_floor_s"] = floor
        if floor is not None and floor > float(target_ttft_s):
            out["feasible"] = False
            out["why"] += (
                f"; INFEASIBLE: static TTFT floor {floor * 1e3:,.1f} ms "
                f"at prompt {prompt_len} exceeds the "
                f"{float(target_ttft_s) * 1e3:,.1f} ms target - no "
                "replica count fixes a per-request floor (shrink the "
                "model, grow prefill_chunk, or relax the SLO)"
            )
    return out


def step_seconds(
    bd, hw: HardwareModel | None = None, *, flops_per_step: float = 0.0
) -> StepTime:
    """Predicted steady-step seconds from a plan's byte/flop terms.

    ``bd`` is a `CostBreakdown` or any mapping exposing ``wire_bytes``,
    ``untraced_grad_sync_bytes``, and ``peak_state_bytes`` (a plan
    manifest's ``chosen`` block qualifies). Model: compute time and
    HBM state-streaming time overlap (take the max - a step reads its
    params+optimizer state at least once), collective wire time and the
    dispatch floor are additive."""
    hw = hw or HardwareModel()

    def get(key):
        if isinstance(bd, dict):
            return float(bd.get(key) or 0.0)
        return float(getattr(bd, key, 0.0) or 0.0)

    compute_s = float(flops_per_step) / hw.flops_per_s
    memory_s = get("peak_state_bytes") / hw.hbm_bytes_per_s
    comm_s = (
        get("wire_bytes") + get("untraced_grad_sync_bytes")
    ) / hw.ici_bytes_per_s
    body = max(compute_s, memory_s)
    if comm_s > body:
        bound = "comm"
    elif compute_s >= memory_s:
        bound = "compute"
    else:
        bound = "memory"
    return StepTime(
        step_s=body + comm_s + hw.step_overhead_s,
        compute_s=compute_s,
        memory_s=memory_s,
        comm_s=comm_s,
        overhead_s=hw.step_overhead_s,
        bound=bound,
        flops_per_step=float(flops_per_step),
        hw=hw.name,
    )
