"""Host-streaming input pipeline for datasets larger than device HBM.

The framework's default data path uploads the whole normalized split to HBM
once and batches on device (`pipeline.py`) - ideal for CIFAR-scale data.
When the dataset does not fit in HBM (or should stay uint8 in host RAM at
1/4 the footprint), this module streams instead: the split is kept as raw
uint8 on the host and each batch is assembled by the native fused
gather+convert+normalize kernel (`native.gather_normalize_u8`, C++
multithreaded; numpy fallback) and shipped to the device(s) per step.

This is the moral equivalent of the reference's torch DataLoader loop
(`data_parallelism_train.py:73-79`: shuffle + batch + normalize on the
host, copy per batch), rebuilt with a fused native kernel and jax
device_put against a mesh sharding instead of pickle sends.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from .. import native
from .pipeline import plan_shape


def prefetch(gen, depth: int = 2):
    """Run generator `gen` in a background thread, keeping up to `depth`
    items assembled ahead of the consumer (double-buffering at depth 2:
    batch t+1 is built on the host while the device runs batch t - r2
    VERDICT weak #5: the synchronous loop starved the device exactly on
    the >HBM datasets streaming exists for).

    Producer exceptions re-raise at the consumer's next pull. The thread
    is a daemon: if the consumer abandons iteration early the producer
    parks on the bounded queue and is reclaimed at process exit.
    """
    q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
    _done, _exc = object(), object()

    def run():
        try:
            for item in gen:
                q.put(item)
            q.put((_done, None))
        except BaseException as e:
            q.put((_exc, e))

    threading.Thread(target=run, daemon=True).start()
    while True:
        item = q.get()
        if (isinstance(item, tuple) and len(item) == 2
                and (item[0] is _done or item[0] is _exc)):
            if item[0] is _exc:
                raise item[1]
            return
        yield item


class HostStream:
    """Shuffled host-side batch stream over an image split.

    images: (N, ...) uint8 (the preferred form - 1/4 host RAM, per-batch
    fused native gather+normalize) or float32 already normalized (plain
    gather passthrough). labels: (N,) int. Each epoch yields
    (images_f32, labels, weight) batches of exactly batch_size rows - the
    final partial batch is padded with repeated row 0 and masked by weight
    0, matching the on-device plan semantics (`pipeline.py`).
    """

    def __init__(self, images, labels, batch_size: int, *,
                 mean: float = 0.5, std: float = 0.5, seed: int = 0):
        self.images = np.ascontiguousarray(images)
        if self.images.dtype not in (np.uint8, np.float32):
            raise TypeError(
                f"HostStream takes uint8 (raw) or float32 (pre-normalized) "
                f"images; got {self.images.dtype}"
            )
        self.labels = np.asarray(labels)
        if len(self.images) != len(self.labels):
            raise ValueError(
                f"{len(self.images)} images vs {len(self.labels)} labels"
            )
        self.batch_size = batch_size
        self.mean, self.std = mean, std
        self._rng = np.random.default_rng(seed)
        self.steps, _ = plan_shape(len(self.images), batch_size)

    def epoch(self, *, shuffle: bool = True):
        """Yield (images (B,...) f32 normalized, labels (B,), w (B,) f32)."""
        n, bs = len(self.images), self.batch_size
        order = self._rng.permutation(n) if shuffle else np.arange(n)
        for step in range(self.steps):
            idx = order[step * bs:(step + 1) * bs]
            w = np.ones(bs, np.float32)
            if len(idx) < bs:
                w[len(idx):] = 0.0
                idx = np.concatenate([idx, np.zeros(bs - len(idx), np.int64)])
            if self.images.dtype == np.uint8:
                x = native.gather_normalize_u8(
                    self.images, idx, self.mean, self.std
                )
            else:  # pre-normalized float32: gather only
                x = self.images[idx]
            yield x, self.labels[idx].astype(np.int32), w
