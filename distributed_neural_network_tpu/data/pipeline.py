"""On-device batching: epoch index plans instead of host DataLoaders.

The reference iterates a torch DataLoader per epoch (shuffle + batch on the
host, copy per batch - `data_parallelism_train.py:73-79,193`). On TPU that
pattern serializes the input pipeline on the host and pays a host->device
transfer per batch. Here the dataset lives in HBM (uploaded once) and an
epoch is described by an **index plan**: a (steps, batch) int32 array of row
indices plus a (steps, batch) float32 weight mask. The plan is computed
*inside jit* from a PRNG key, so a whole training epoch - shuffle included -
runs as one compiled `lax.scan` with zero host involvement.

Semantics parity:
- shuffle=True per epoch for train (`data_parallelism_train.py:76`),
  sequential for eval (`:88-91`);
- torch DataLoader keeps the final partial batch (no drop_last); we keep it
  too by padding the last batch and masking padded rows with weight 0, which
  preserves static shapes for XLA while matching per-sample loss/grad math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def plan_shape(n_rows: int, batch_size: int) -> tuple[int, int]:
    """(steps, batch) for a split of n_rows - final partial batch kept."""
    if n_rows <= 0:
        raise ValueError(f"n_rows must be positive, got {n_rows}")
    steps = -(-n_rows // batch_size)  # ceil
    return steps, batch_size


def epoch_plan(key: jax.Array, n_rows: int, batch_size: int):
    """Shuffled epoch index plan, built on device.

    Returns (idx, w): idx (steps, batch) int32 row indices into the split,
    w (steps, batch) float32 {0,1} validity mask (0 marks padding rows in the
    final partial batch). Static args n_rows/batch_size make this jit-stable.
    """
    steps, bs = plan_shape(n_rows, batch_size)
    perm = jax.random.permutation(key, n_rows)
    return _pad_and_reshape(perm, n_rows, steps, bs)


def eval_plan(n_rows: int, batch_size: int):
    """Sequential (unshuffled) index plan for evaluation."""
    steps, bs = plan_shape(n_rows, batch_size)
    return _pad_and_reshape(jnp.arange(n_rows, dtype=jnp.int32), n_rows, steps, bs)


def _pad_and_reshape(order: jax.Array, n_rows: int, steps: int, bs: int):
    pad = steps * bs - n_rows
    idx = jnp.concatenate([order.astype(jnp.int32), jnp.zeros(pad, jnp.int32)])
    w = jnp.concatenate([jnp.ones(n_rows, jnp.float32), jnp.zeros(pad, jnp.float32)])
    return idx.reshape(steps, bs), w.reshape(steps, bs)


def gather_batch(images: jax.Array, labels: jax.Array, idx: jax.Array):
    """Form one batch on device by row gather (jnp.take along axis 0).

    The optimization barrier pins the layout boundary at the *batch*: without
    it, XLA's layout assignment hoists the conv-friendly relayout of the
    gather operand out of the epoch scan and materializes the ENTIRE dataset
    in conv layout - which pads the channel dim 3->128 on TPU (42x memory,
    e.g. 26 GB for CIFAR-10 train at batch_size 1, an HBM OOM at compile
    time). With the barrier, only the (batch, ...) slice is relaid per step.
    """
    x = jnp.take(images, idx, axis=0)
    y = jnp.take(labels, idx, axis=0)
    return jax.lax.optimization_barrier((x, y))
