"""Token-stream dataset for the LM family: memmapped corpus -> (B, S) batches.

The reference has no text/LM capability at all (its only dataset is
CIFAR-10, `data_parallelism_train.py:24-27`); this module is the LM
counterpart of `data/cifar10.py`: a zero-copy host-side corpus reader
that feeds `train/lm.py` without any tokenizer dependency - bring tokens
as a flat binary/npy file (uint16/uint32/int32, the GPT-2/nanoGPT-style
"one long token stream" convention).

TPU-shaped pipeline:
- the corpus stays a numpy memmap on host (no HBM residency; works for
  corpora far beyond device memory),
- a batch is B contiguous windows of S+1 tokens sampled at seeded
  offsets; (tokens, targets) = (w[:-1], w[1:]) - one host gather per
  step, transferred once,
- deterministic: offsets come from a seeded numpy Generator keyed by
  (seed, step), so any batch is reproducible in isolation (resume-safe),
- an optional held-out split reserves the stream tail for eval windows.

No torch, no HF: loading is pure numpy; the synthetic fallback inside
`load_token_stream` generates a copy-task stream so every test and CLI
path runs with zero files.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

_SUPPORTED = {
    np.dtype(np.uint16): np.uint16,
    np.dtype(np.uint32): np.uint32,
    np.dtype(np.int32): np.int32,
    np.dtype(np.int64): np.int64,
}


@dataclass(frozen=True)
class TokenStream:
    """A flat token stream plus its train/eval boundary."""

    tokens: np.ndarray  # 1-D, integer dtype (often a memmap)
    n_train: int  # tokens [0, n_train) are the train split
    vocab_size: int
    source: str  # "npy" | "bin" | "txt" | "synthetic"

    @property
    def n_eval(self) -> int:
        return len(self.tokens) - self.n_train


def load_token_stream(
    path: str | None,
    *,
    vocab_size: int,
    eval_frac: float = 0.05,
    bin_dtype: str = "uint16",
    synthetic_tokens: int = 1 << 16,
    seed: int = 0,
) -> TokenStream:
    """Load a token corpus, or synthesize one when `path` is None/missing.

    `path` may be a .npy (any supported integer dtype) or a raw .bin of
    `bin_dtype` tokens. Values must be < vocab_size (checked on a sample,
    fully lazily for memmaps). The trailing `eval_frac` of the stream is
    reserved as the held-out split.
    """
    if not 0.0 <= eval_frac < 1.0:
        raise ValueError(f"eval_frac must be in [0, 1), got {eval_frac}")
    if path and os.path.exists(path):
        if path.endswith(".npy"):
            arr = np.load(path, mmap_mode="r")
            source = "npy"
        elif path.endswith(".txt"):
            # byte-level tokenization IS a uint8 memmap of the text file:
            # zero-copy, no tokenizer dependency; needs vocab_size >= 256
            if vocab_size < 256:
                raise ValueError(
                    f".txt corpora are byte-tokenized (ids 0-255); "
                    f"vocab_size must be >= 256, got {vocab_size}"
                )
            arr = np.memmap(path, dtype=np.uint8, mode="r")
            source = "txt"
        else:
            arr = np.memmap(path, dtype=np.dtype(bin_dtype), mode="r")
            source = "bin"
        if arr.ndim != 1:
            raise ValueError(
                f"token file must be 1-D, got shape {arr.shape} ({path})"
            )
        if source != "txt" and arr.dtype not in _SUPPORTED:
            raise ValueError(
                f"unsupported token dtype {arr.dtype} ({path}); use one of "
                f"{sorted(str(d) for d in _SUPPORTED)}"
            )
        # cheap sanity probe on a deterministic sample (full scan of a
        # 100 GB memmap would defeat the point of memmapping)
        probe = np.asarray(
            arr[np.linspace(0, len(arr) - 1, num=min(4096, len(arr)),
                            dtype=np.int64)]
        )
        if probe.size and int(probe.max()) >= vocab_size:
            raise ValueError(
                f"token id {int(probe.max())} >= vocab_size {vocab_size} "
                f"in {path}"
            )
    else:
        if path:
            raise FileNotFoundError(
                f"token file {path!r} not found (pass --data-path to an "
                "existing .npy/.bin/.txt or omit it for the synthetic "
                "stream)"
            )
        # synthetic: concatenated copy-task sequences so the LM objective
        # is learnable and convergence is observable without a corpus
        rng = np.random.default_rng(seed)
        half = 64
        n_seq = max(synthetic_tokens // (2 * half), 1)
        first = rng.integers(2, vocab_size, size=(n_seq, half))
        arr = np.concatenate([first, first], axis=1).reshape(-1)
        arr = arr.astype(np.uint32)
        source = "synthetic"
    n_eval = int(len(arr) * eval_frac)
    return TokenStream(
        tokens=arr, n_train=len(arr) - n_eval, vocab_size=vocab_size,
        source=source,
    )


def _window_starts(
    rng: np.random.Generator, lo: int, hi: int, batch: int
) -> np.ndarray:
    if hi <= lo:
        raise ValueError(
            f"split has too few tokens for this seq_len (window range "
            f"[{lo}, {hi}))"
        )
    return rng.integers(lo, hi, size=batch)


def sample_batch(
    stream: TokenStream,
    *,
    batch: int,
    seq_len: int,
    step: int,
    seed: int = 0,
    split: str = "train",
):
    """(tokens, targets) int32 (batch, seq_len) for `step` of `split`.

    Windows are contiguous slices of seq_len + 1 tokens at offsets drawn
    from a Generator keyed by (seed, split, step) - stateless, so resume
    at step k reproduces exactly the batches a fresh run would see.
    """
    if split == "train":
        lo, hi = 0, stream.n_train - seq_len - 1
    elif split == "eval":
        lo, hi = stream.n_train, len(stream.tokens) - seq_len - 1
    else:
        raise ValueError(f"split must be 'train' or 'eval', got {split!r}")
    # fixed per-split constants: Python's hash() is salted per process
    # (PYTHONHASHSEED), which would silently void the cross-process
    # determinism this function guarantees
    rng = np.random.default_rng(
        np.random.SeedSequence([seed, {"train": 0, "eval": 1}[split], step])
    )
    starts = _window_starts(rng, lo, hi, batch)
    idx = starts[:, None] + np.arange(seq_len + 1)[None, :]
    w = np.asarray(stream.tokens[idx], dtype=np.int32)
    return w[:, :-1], w[:, 1:]
