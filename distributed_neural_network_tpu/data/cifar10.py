"""CIFAR-10 data source for the TPU-native framework.

Capability parity with the reference's torchvision pipeline
(`data_parallelism_train.py:24-27,69-79`, `single_proc_train.py:31-45`):
CIFAR-10 train/test arrays normalized with mean 0.5 / std 0.5 per channel.

TPU-first design: there is no per-batch host Dataset/DataLoader object. The
whole split is materialized once as a contiguous numpy array, uploaded to
device HBM **once**, and per-epoch batches are formed *on device* by integer
gather (see `pipeline.py`). This removes the host->device transfer from the
epoch path entirely - the torch DataLoader's per-batch pickle/copy cost
(the reference's "data loading time" phase, `log/*_children.txt:1`) becomes a
one-time upload.

Offline environments: this build runs with zero network egress, so unlike
torchvision (`download=True`) we never download. Sources, in order:

1. ``{root}/cifar-10-batches-py/`` - the standard python pickle batches
   (same on-disk format torchvision produces), so a directory prepared for
   the reference works unchanged here.
2. ``{root}/cifar10.npz`` with keys x_train/y_train/x_test/y_test.
3. ``synthetic`` - a deterministic, seeded, class-structured stand-in with
   identical shapes/dtypes (10 fixed class templates + noise), so every
   training regime, benchmark, and test runs without the real dataset.
   Accuracy numbers on synthetic data are NOT comparable to BASELINE.md;
   wall-clock numbers are (same shapes, same FLOPs).
"""

from __future__ import annotations

import os
import pickle
import tarfile
from dataclasses import dataclass

import numpy as np

CIFAR10_MEAN = 0.5
CIFAR10_STD = 0.5
NUM_CLASSES = 10
IMAGE_SHAPE = (32, 32, 3)
TRAIN_SIZE = 50_000
TEST_SIZE = 10_000


@dataclass(frozen=True)
class Split:
    """One split as host numpy arrays, NHWC.

    images are normalized float32 in [-1, 1] by default; with
    `load_split(normalize_images=False)` they stay raw uint8 (the
    host-streaming mode's storage form)."""

    images: np.ndarray  # (N, 32, 32, 3) float32 in [-1, 1] (or uint8 raw)
    labels: np.ndarray  # (N,) int32
    source: str  # "pickle", "npz", or "synthetic"

    def __len__(self) -> int:
        return int(self.images.shape[0])


def normalize(images_u8: np.ndarray) -> np.ndarray:
    """uint8 [0,255] -> float32 in [-1,1]: (x/255 - 0.5)/0.5.

    Parity: reference transforms.Normalize((0.5,)*3, (0.5,)*3)
    (`data_parallelism_train.py:24-27`). uint8 input runs through the
    native C++ kernel when available (single fused pass); any other
    numeric dtype (e.g. a float-typed npz) keeps the plain numpy math.
    """
    images_u8 = np.asarray(images_u8)
    if images_u8.dtype == np.uint8:
        from .. import native

        return native.normalize_u8(images_u8, CIFAR10_MEAN, CIFAR10_STD)
    x = images_u8.astype(np.float32) / 255.0
    return (x - CIFAR10_MEAN) / CIFAR10_STD


def _load_pickle_batches_u8(batch_dir: str, train: bool):
    """Decode python batches to raw uint8 NHWC (streaming-mode storage)."""
    names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    imgs, labels = [], []
    for name in names:
        with open(os.path.join(batch_dir, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        labels.append(np.asarray(d[b"labels"], dtype=np.int32))
    return np.ascontiguousarray(np.concatenate(imgs)), np.concatenate(labels)


def _load_pickle_batches_normalized(batch_dir: str, train: bool):
    """Decode python batches straight to normalized NHWC float32.

    The (N, 3072) plane-major rows go through ONE fused native pass
    (layout change + dtype + normalize; numpy chain as fallback) instead of
    reshape/transpose/astype/affine with an intermediate per step.
    """
    from .. import native

    names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    imgs, labels = [], []
    for name in names:
        path = os.path.join(batch_dir, name)
        with open(path, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs.append(
            native.cifar_decode_normalize(d[b"data"], CIFAR10_MEAN, CIFAR10_STD)
        )
        labels.append(np.asarray(d[b"labels"], dtype=np.int32))
    return np.concatenate(imgs), np.concatenate(labels)


def _maybe_extract_tarball(root: str) -> None:
    batch_dir = os.path.join(root, "cifar-10-batches-py")
    tar = os.path.join(root, "cifar-10-python.tar.gz")
    if not os.path.isdir(batch_dir) and os.path.isfile(tar):
        with tarfile.open(tar, "r:gz") as tf:
            tf.extractall(root)  # noqa: S202 - trusted local archive


def make_synthetic(
    n: int, *, seed: int = 0, num_classes: int = NUM_CLASSES, train: bool = True
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic class-structured synthetic CIFAR stand-in (uint8).

    Each class has a fixed low-frequency template; samples are template +
    Gaussian noise, so the LeNet CNN can genuinely learn (accuracy well above
    chance), making convergence tests meaningful without the real dataset.
    Train and test are drawn from the same distribution with disjoint streams.
    """
    rng = np.random.default_rng(seed + (0 if train else 1_000_003))
    tmpl_rng = np.random.default_rng(seed)  # templates shared by train/test
    # low-frequency templates: 8x8 upsampled to 32x32 so conv k5 can see them
    small = tmpl_rng.uniform(40.0, 215.0, size=(num_classes, 8, 8, 3))
    templates = np.repeat(np.repeat(small, 4, axis=1), 4, axis=2)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    noise = rng.normal(0.0, 32.0, size=(n, *IMAGE_SHAPE))
    images = np.clip(templates[labels] + noise, 0, 255).astype(np.uint8)
    return images, labels


def default_root() -> str:
    return os.environ.get("CIFAR10_DIR", os.path.join(os.getcwd(), "data"))


def load_split(
    train: bool,
    *,
    root: str | None = None,
    source: str = "auto",
    synthetic_size: int | None = None,
    seed: int = 0,
    normalize_images: bool = True,
) -> Split:
    """Load one CIFAR-10 split.

    source: "auto" (real data if present, else synthetic), "pickle", "npz",
    or "synthetic". `normalize_images=False` keeps uint8 pixel data where
    the source provides it (the host-streaming input mode normalizes
    per-batch in the native kernel, and u8 host storage is 1/4 the RAM);
    float-typed npz sources are normalized regardless.
    """
    root = root or default_root()
    if source in ("auto", "pickle"):
        _maybe_extract_tarball(root) if os.path.isdir(root) else None
        batch_dir = os.path.join(root, "cifar-10-batches-py")
        if os.path.isdir(batch_dir):
            if normalize_images:
                x, y = _load_pickle_batches_normalized(batch_dir, train)
            else:
                x, y = _load_pickle_batches_u8(batch_dir, train)
            return Split(x, y, "pickle")
        if source == "pickle":
            raise FileNotFoundError(f"no cifar-10-batches-py under {root}")
    if source in ("auto", "npz"):
        npz = os.path.join(root, "cifar10.npz")
        if os.path.isfile(npz):
            d = np.load(npz)
            x = d["x_train"] if train else d["x_test"]
            y = d["y_train"] if train else d["y_test"]
            if normalize_images or x.dtype != np.uint8:
                x = normalize(x)
            return Split(x, y.reshape(-1).astype(np.int32), "npz")
        if source == "npz":
            raise FileNotFoundError(f"no cifar10.npz under {root}")
    # synthetic fallback
    n = synthetic_size or (TRAIN_SIZE if train else TEST_SIZE)
    x, y = make_synthetic(n, seed=seed, train=train)
    return Split(normalize(x) if normalize_images else x, y, "synthetic")
