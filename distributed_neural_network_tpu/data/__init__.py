"""Subpackage: data."""
