"""distributed_neural_network_tpu - a TPU-native distributed training framework.

A from-scratch JAX/XLA re-design of the capabilities of
dat-rohit/distributed-neural-network (see SURVEY.md): CIFAR-10 CNN training
under three regimes - single-device, model replication, and data parallelism
with epoch-wise parameter averaging - plus fault simulation, phase timing,
metrics, and the reference's CLI surface, all expressed over a
`jax.sharding.Mesh` with XLA collectives instead of MPI point-to-point.
"""

from .data.cifar10 import Split, load_split, make_synthetic, normalize
from .models.cnn import Network, param_count
from .parallel.mesh import DATA_AXIS, create_mesh, device_count
from .train.engine import Engine, EpochMetrics, TrainConfig

__version__ = "0.1.0"

__all__ = [
    "DATA_AXIS",
    "Engine",
    "EpochMetrics",
    "Network",
    "Split",
    "TrainConfig",
    "create_mesh",
    "device_count",
    "load_split",
    "make_synthetic",
    "normalize",
    "param_count",
    "__version__",
]
