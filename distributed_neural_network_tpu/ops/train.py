"""Compiled training and evaluation epochs (lax.scan over on-device batches).

This replaces the reference's Python hot loops - `run_child`'s per-batch
forward/backward/step (`data_parallelism_train.py:193-203`, ~98% of
wall-clock per `log/bs16_log_epochs25_proc4_children.txt:2`) and the parent's
serial eval (`:157-183`) - with whole-epoch XLA programs: the dataset lives in
HBM, the per-epoch shuffle is a device-side PRNG permutation, and every batch
step is one iteration of a `lax.scan`, so an entire epoch is a single device
dispatch with zero host round-trips.

Semantics knobs (SURVEY.md section 7 "Hard parts" - semantics, not speed):
- `reset_momentum`: True reproduces the reference's observable dynamics of
  re-creating the optimizer each epoch (`data_parallelism_train.py:187`).
- `grad_sync_axis`: None = faithful local SGD (parameter averaging happens
  only at the epoch edge, in `parallel/collectives.py`); an axis name =
  idiomatic per-step gradient pmean DP - a *different* optimizer, offered as
  the fast path and labelled as such.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..data.pipeline import epoch_plan, eval_plan, gather_batch
from .losses import masked_correct, masked_cross_entropy
from .sgd import init_momentum, sgd_step


def make_batch_loss(apply_fn):
    def batch_loss(params, x, y, w):
        logits = apply_fn({"params": params}, x)
        return masked_cross_entropy(logits, y, w)

    return batch_loss


def sync_grads(grads, axis: str, *, grad_sync: str = "end",
               bucket_bytes: int | None = None):
    """Per-step gradient pmean over `axis`, as one collective per leaf
    ("end", the default) or one per size-capped contiguous leaf bucket
    ("overlap" - parallel/collectives.py bucketing; the bucketed form
    hands XLA's latency-hiding scheduler independent collectives it can
    start while the backward of still-unsynced buckets runs). Values are
    identical either way - bucketing repartitions the same elementwise
    mean. Shared by the HBM epoch scan and the streaming per-batch step
    so the two paths cannot drift."""
    if grad_sync != "overlap":
        return jax.tree.map(lambda g: jax.lax.pmean(g, axis), grads)
    from ..parallel.collectives import (
        DEFAULT_BUCKET_BYTES,
        bucketed_psum,
        plan_buckets,
    )

    layout = plan_buckets(
        grads, bucket_bytes=bucket_bytes or DEFAULT_BUCKET_BYTES
    )
    return bucketed_psum(grads, layout, axis, mean=True)


def make_train_epoch(
    apply_fn,
    *,
    lr: float,
    momentum: float,
    n_rows: int,
    batch_size: int,
    reset_momentum: bool = True,
    grad_sync_axis: str | None = None,
    grad_sync: str = "end",
    bucket_bytes: int | None = None,
):
    """Build f(params, mom, images, labels, key) -> (params, mom, loss_sum, n_batches).

    One full epoch of SGD as a single scan. `loss_sum`/`n_batches` mirror the
    reference child's `total_loss`/`total_batches` accounting
    (`data_parallelism_train.py:201-202`) - per-batch mean losses summed, and
    the *batch count* as denominator material (the reference's key-count bug,
    SURVEY.md section 2, is fixed downstream). With `grad_sync_axis` set
    (per-step gradient-pmean DP), `grad_sync`/`bucket_bytes` select the
    collective granularity (`sync_grads`).
    """
    batch_loss = make_batch_loss(apply_fn)
    grad_fn = jax.value_and_grad(batch_loss)

    def epoch(params, mom, images, labels, key):
        idx, w = epoch_plan(key, n_rows, batch_size)
        if reset_momentum:
            mom = init_momentum(params)

        def step(carry, xs):
            params, mom = carry
            bidx, bw = xs
            x, y = gather_batch(images, labels, bidx)
            loss, grads = grad_fn(params, x, y, bw)
            if grad_sync_axis is not None:
                grads = sync_grads(
                    grads, grad_sync_axis, grad_sync=grad_sync,
                    bucket_bytes=bucket_bytes,
                )
            params, mom = sgd_step(params, mom, grads, lr, momentum)
            return (params, mom), loss

        (params, mom), losses = jax.lax.scan(step, (params, mom), (idx, w))
        n_batches = jnp.float32(losses.shape[0])
        return params, mom, losses.sum(), n_batches

    return epoch


def make_eval_epoch(apply_fn, *, n_rows: int, batch_size: int):
    """Build f(params, images, labels, row_weights) -> (loss_sum, n_batches, correct, n_valid).

    Mirrors the reference `eval` (`data_parallelism_train.py:157-183`):
    per-batch mean CE collected then averaged over batches (`np.mean(losses)`,
    `:177`), top-1 correct count, total valid samples. `row_weights` masks
    padded rows (sharded eval pads the split to equal per-device sizes);
    batches with zero valid rows are excluded from the batch count so the
    batch-mean average matches the reference's serial computation.
    """

    def epoch(params, images, labels, row_weights):
        idx, w = eval_plan(n_rows, batch_size)

        def step(_, xs):
            bidx, bw = xs
            x, y = gather_batch(images, labels, bidx)
            rw = jnp.take(row_weights, bidx, axis=0) * bw
            logits = apply_fn({"params": params}, x)
            loss = masked_cross_entropy(logits, y, rw)
            correct = masked_correct(logits, y, rw)
            valid = rw.sum()
            return None, (loss, correct, valid)

        _, (losses, corrects, valids) = jax.lax.scan(step, None, (idx, w))
        batch_has_valid = (valids > 0).astype(jnp.float32)
        loss_sum = (losses * batch_has_valid).sum()
        n_batches = batch_has_valid.sum()
        return loss_sum, n_batches, corrects.sum(), valids.sum()

    return epoch
