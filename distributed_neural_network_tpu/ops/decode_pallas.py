"""Single-query KV-cache decode attention kernel (Pallas TPU, fwd-only).

Why a dedicated kernel when `ops/flash_pallas.py` already exists: decode
attends ONE query row per step against a static-size cache, and the r5
probes put the XLA lowering of that step ~4x above its HBM-bandwidth
bound at batch (2.60 ms/step at b16/hd64/cache 640 vs ~0.4 ms of
unavoidable traffic; b1 IS at the bound, so the gap is the per-step
small-op chain, not cache size). The training flash kernel cannot help:
its q axis is a full sequence. This kernel is the decode-shaped
counterpart:

- **One fused pass**: scores, online softmax, and the value gather run
  in a single `pallas_call` per layer-step - no (B, H, total) f32 score
  tensor round-trips through HBM between three XLA ops.
- **Dead-block skipping**: the XLA path attends the FULL padded cache
  every step and masks (static shapes - the design is right, the work
  is not). Here the grid still covers total/bk blocks, but a block
  whose first column is past `pos` skips compute under `pl.when` and
  clamps its index_map to the boundary block (already resident, no new
  DMA) - per-step cache traffic is proportional to the LIVE prefix,
  not the allocation. `pos` rides scalar prefetch
  (`pltpu.PrefetchScalarGridSpec`) so index_maps can use it.
- **Per-sequence positions**: ``pos`` may be a scalar (the
  `models/transformer.py generate` path - every sequence at the same
  position) or a ``(B,)`` vector - the serving engine's continuous
  batch, where every slot sits at its own depth (serve/engine.py routes
  this kernel under the paged gather). The mask and the skip clamp
  resolve per (batch, head) lane from the prefetched vector.
- **int8 K/V stream** (`k_scale`/`v_scale` given): the caches arrive in
  int8 with per-slot f32 scales (lane-replicated, the same layout as
  flash's lse residual) and each k-block is dequantized IN the k-block
  loop right before its dot - HBM cache traffic is halved (decode's
  actual roofline; see the measured-outcome note below), the MXU dots
  stay in the query dtype. This is the serving int8 KV cache's fused
  read path (serve/kv_cache.py stores per-(block, head) scales; the
  engine expands them to per-slot at gather time).
- **Single-row query on a (8, 128) grid**: Mosaic blocks must tile
  (8, 128), so the one real query row is lane-broadcast to 8 sublanes
  by the caller and row 0 of the output is read back - 7 redundant rows
  cost nothing (the MXU pass is the same) and keep every block legal.
- Numerics: f32 dot accumulation + f32 online-softmax recurrence
  (m/l/acc in VMEM scratch), matching `flash_pallas` conventions;
  parity with the XLA decode path is pinned by
  `tests/test_decode_pallas.py` up to blockwise reassociation, and the
  int8 path by `tests/test_quant.py` against the dequantized oracle.

The reference framework has no attention at all (its model is the
5-layer CNN, `/root/reference/models/model.py:9-27`); this kernel is
part of the beyond-reference LM family's inference path
(`models/transformer.py generate`).

**Measured outcome (r5, TPU v5e, the honest negative result)**: at the
decode bench shapes (d512, cache <= 640) this kernel LOSES to the XLA
chain it replaces - 3.69 vs 2.59 ms/step at b16/hd64 in-loop, and
+~25% isolated at every block size. XLA lowers the einsum/softmax/
einsum step as one well-tiled batched matmul chain over all B*H heads;
a per-layer `pallas_call` costs more than the fusion saves, and
dead-block skipping cannot pay at 640-slot caches. `generate` therefore
defaults to the XLA path (`DNN_TPU_DECODE_IMPL=auto`); the kernel stays
selectable (`=pallas`) and parity-tested for the long-cache regime
where skipping's traffic advantage grows linearly - and the int8 stream
halves exactly the traffic that regime is bound by.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_pallas import _CompilerParams, _divisor_block, _struct

_LANES = 128
_SUBLANES = 8
_NEG_BIG = -1e30


def _dot_nt(a, b):
    """a (m, d) x b (n, d) -> (m, n), f32 accumulation (q @ k^T)."""
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_nn(a, b):
    """a (m, n) x b (n, d) -> (m, d), f32 accumulation (p @ v)."""
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc,
                   *, bk, scale, heads):
    bh, kj = pl.program_id(0), pl.program_id(1)
    n_k = pl.num_programs(1)
    pos = pos_ref[bh // heads]

    @pl.when(kj == 0)
    def _init():
        m_sc[...] = jnp.full(m_sc.shape, _NEG_BIG, m_sc.dtype)
        l_sc[...] = jnp.zeros(l_sc.shape, l_sc.dtype)
        acc_sc[...] = jnp.zeros(acc_sc.shape, acc_sc.dtype)

    def _step():
        q = q_ref[0]  # (8, d) - row 0 real, rows 1-7 broadcast copies
        s = _dot_nt(q, k_ref[0]) * scale  # (8, bk) f32
        cols = kj * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols <= pos, s, _NEG_BIG)
        m = m_sc[...][:, :1]
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l_sc[...][:, :1] * alpha + p.sum(-1, keepdims=True)
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)
        acc_sc[...] = acc_sc[...] * alpha + _dot_nn(
            p.astype(v_ref.dtype), v_ref[0]
        )

    # a block whose first column is past pos is fully masked: skip it
    # (its index_map already re-points at the boundary block - no DMA)
    pl.when(kj * bk <= pos)(_step)

    @pl.when(kj == n_k - 1)
    def _finish():
        l = jnp.maximum(l_sc[...][:, :1], 1e-30)
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)


def _decode_kernel_q8(pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                      m_sc, l_sc, acc_sc, *, bk, scale, heads):
    """int8-stream variant: k/v blocks arrive int8 with per-slot f32
    scales (lane-replicated); dequantization is fused into the k-block
    loop - the block is widened to the query dtype right before its dot,
    so the int8 bytes are all that ever crosses HBM for the cache."""
    bh, kj = pl.program_id(0), pl.program_id(1)
    n_k = pl.num_programs(1)
    pos = pos_ref[bh // heads]

    @pl.when(kj == 0)
    def _init():
        m_sc[...] = jnp.full(m_sc.shape, _NEG_BIG, m_sc.dtype)
        l_sc[...] = jnp.zeros(l_sc.shape, l_sc.dtype)
        acc_sc[...] = jnp.zeros(acc_sc.shape, acc_sc.dtype)

    def _step():
        q = q_ref[0]  # (8, d) query dtype
        sk = ks_ref[0][:, :1]  # (bk, 1) f32 per-slot scales
        k_f = (k_ref[0].astype(jnp.float32) * sk).astype(q.dtype)
        s = _dot_nt(q, k_f) * scale  # (8, bk) f32
        cols = kj * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(cols <= pos, s, _NEG_BIG)
        m = m_sc[...][:, :1]
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l_sc[...][:, :1] * alpha + p.sum(-1, keepdims=True)
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)
        sv = vs_ref[0][:, :1]
        v_f = (v_ref[0].astype(jnp.float32) * sv).astype(q.dtype)
        acc_sc[...] = acc_sc[...] * alpha + _dot_nn(p.astype(q.dtype), v_f)

    pl.when(kj * bk <= pos)(_step)

    @pl.when(kj == n_k - 1)
    def _finish():
        l = jnp.maximum(l_sc[...][:, :1], 1e-30)
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)


def decode_cache_attention(q, ck, cv, pos, *, block_k: int = 512,
                           interpret: bool = False,
                           k_scale=None, v_scale=None):
    """One cached decode step of attention for every (batch, head).

    q (B, H, Dh) - the current position's query rows;
    ck/cv (B, H, total, Dh) - the static KV caches, in q's dtype, OR
    int8 when ``k_scale``/``v_scale`` (B, H, total) f32 per-slot scales
    are given (the serving engine's quantized pool read: dequantization
    fuses into the k-block loop);
    pos - scalar int32 (every sequence at the same position - the
    `generate` loop) or (B,) int32 per-sequence positions (the serving
    engine's continuous batch; cols > pos[b] are dead for batch b).
    Returns o (B, H, Dh). `total` must admit a sublane-legal block
    (gate with `decode_kernel_ok(total)`; enforced here too, so a direct
    caller gets the documented ValueError instead of a Mosaic tiling
    failure deep in the compile); scale is 1/sqrt(Dh) applied here.
    """
    b, h, total, d = ck.shape
    quantized = k_scale is not None or v_scale is not None
    if quantized and (k_scale is None or v_scale is None):
        raise ValueError(
            "quantized decode needs BOTH k_scale and v_scale "
            "(per-slot f32, shape (B, H, total))"
        )
    bk = _divisor_block(block_k, total)
    if not decode_kernel_ok(total, block_k, quantized=quantized):
        raise ValueError(
            f"decode_cache_attention: cache size {total} admits no "
            f"sublane-legal k block at block_k={block_k} (largest "
            f"divisor {bk} is not a multiple of "
            f"{32 if quantized else 16}, the Mosaic sublane tile for "
            f"{'int8' if quantized else 'bf16'}) - pick a total with "
            "such a divisor (any multiple of 128 works) or fall back "
            "to the XLA decode path"
        )
    q8 = jnp.broadcast_to(
        q.reshape(b * h, 1, d), (b * h, _SUBLANES, d)
    )
    kf = ck.reshape(b * h, total, d)
    vf = cv.reshape(b * h, total, d)
    pos_arr = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32).reshape(-1), (b,)
    ) if jnp.ndim(pos) <= 1 else None
    if pos_arr is None or pos_arr.shape != (b,):
        raise ValueError(
            f"pos must be a scalar or shape ({b},), got "
            f"{jnp.shape(pos)}"
        )

    def kv_index(b_, j, pos_ref):
        # skipped steps are the suffix (blocks past this sequence's
        # pos): re-point at the boundary block, which the last live
        # step left resident
        return (b_, jnp.minimum(j, pos_ref[b_ // h] // bk), 0)

    in_specs = [
        pl.BlockSpec((1, _SUBLANES, d), lambda b_, j, p_: (b_, 0, 0)),
        pl.BlockSpec((1, bk, d), kv_index),
        pl.BlockSpec((1, bk, d), kv_index),
    ]
    operands = [q8, kf, vf]
    if quantized:
        # per-slot scales ride lane-replicated (the flash lse layout):
        # a (total,) row vector is not a Mosaic-legal block
        ks_l = jnp.broadcast_to(
            k_scale.astype(jnp.float32).reshape(b * h, total)[..., None],
            (b * h, total, _LANES),
        )
        vs_l = jnp.broadcast_to(
            v_scale.astype(jnp.float32).reshape(b * h, total)[..., None],
            (b * h, total, _LANES),
        )
        in_specs += [
            pl.BlockSpec((1, bk, _LANES), kv_index),
            pl.BlockSpec((1, bk, _LANES), kv_index),
        ]
        operands += [ks_l, vs_l]
        kernel = functools.partial(
            _decode_kernel_q8, bk=bk, scale=1.0 / float(d) ** 0.5, heads=h
        )
    else:
        kernel = functools.partial(
            _decode_kernel, bk=bk, scale=1.0 / float(d) ** 0.5, heads=h
        )

    o = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * h, total // bk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, _SUBLANES, d), lambda b_, j, p_: (b_, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((_SUBLANES, _LANES), jnp.float32),  # running max
                pltpu.VMEM((_SUBLANES, _LANES), jnp.float32),  # denom
                pltpu.VMEM((_SUBLANES, d), jnp.float32),       # accumulator
            ],
        ),
        out_shape=_struct((b * h, _SUBLANES, d), q.dtype, q, ck, cv),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(pos_arr, *operands)
    return o[:, 0].reshape(b, h, d)


def decode_kernel_ok(total: int, block_k: int = 512, *,
                     quantized: bool = False) -> bool:
    """True when the kernel's block constraints hold at this cache size:
    the chosen k block must be sublane-tileable for EVERY supported
    cache dtype - bf16's Mosaic tile is (16, 128), f32's is (8, 128),
    so the gate requires the stricter 16 (the head-dim block is always
    the full axis, which Mosaic accepts at any size); int8/fp8 caches
    (``quantized=True``) tile at (32, 128), so their gate requires 32.
    Pass the same block_k the kernel will run with - the gate validates
    the block actually used. Tiny or awkward totals fall back to the
    XLA path."""
    tile = 4 * _SUBLANES if quantized else 2 * _SUBLANES
    return _divisor_block(block_k, total) % tile == 0
