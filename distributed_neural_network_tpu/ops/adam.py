"""Adam/AdamW as pure tree ops.

Beyond-reference capability: the reference's only optimizer is
`torch.optim.SGD(lr, momentum)` (`data_parallelism_train.py:187`); a
framework at this scale needs the adaptive family too. Same shape contract
as `ops/sgd.py` - pure functions over parameter pytrees, layout-oblivious
(elementwise), so they run replicated, tensor-sharded, or ZeRO-sharded
(`parallel/zero.py zero_adam_step_sharded`) unchanged. Numerics follow the
standard bias-corrected Adam (Kingma & Ba) with optional decoupled weight
decay (AdamW, Loshchilov & Hutter); parity with optax.adam is pinned by
tests/test_adam.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_adam(params):
    """Zero first/second-moment trees + step counter."""
    return {
        "m": jax.tree.map(jnp.zeros_like, params),
        "v": jax.tree.map(jnp.zeros_like, params),
        "t": jnp.zeros((), jnp.int32),
    }


def bias_corrections(t, b1: float, b2: float):
    """(c1, c2) bias-correction divisors at integer step t (1-based)."""
    tf = t.astype(jnp.float32)
    return 1.0 - b1 ** tf, 1.0 - b2 ** tf


def adam_leaf_update(
    p, g, m, v, c1, c2, lr, b1, b2, eps, weight_decay
):
    """Elementwise Adam/AdamW update for one leaf (or leaf shard).

    The single source of truth for the update math - `adam_step` (full
    trees) and `parallel/zero.py zero_adam_step_sharded` (per-leaf shards)
    both apply exactly this function, which is what makes the ZeRO
    variant's "numerics match ops/adam.py" contract structural rather
    than copy-maintained. Returns (new_p, new_m, new_v).
    """
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * (g * g)
    step = (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
    if weight_decay:
        step = step + weight_decay * p
    return p - lr * step, m_new, v_new


def adam_step(
    params,
    state,
    grads,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
):
    """One (bias-corrected) Adam/AdamW update; returns (params, state)."""
    t = state["t"] + 1
    c1, c2 = bias_corrections(t, b1, b2)
    new = jax.tree.map(
        lambda p, g, m, v: adam_leaf_update(
            p, g, m, v, c1, c2, lr, b1, b2, eps, weight_decay
        ),
        params, grads, state["m"], state["v"],
    )
    outer = jax.tree.structure(params)
    inner = jax.tree.structure((0, 0, 0))
    p_new, m, v = jax.tree.transpose(outer, inner, new)
    return p_new, {"m": m, "v": v, "t": t}


def guarded_adam_step(
    params,
    state,
    grads,
    lr,
    *,
    ok,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
):
    """`adam_step` gated on the traced scalar `ok`: when False the whole
    update passes through unchanged - params, both moments, AND the step
    counter `t` (a skipped step must not advance the bias correction) -
    the guard's in-jit 'skip' (train/guard.py). With `ok=True` the result
    is bitwise identical to the unguarded path."""
    from .schedule import tree_where

    new_p, new_s = adam_step(
        params, state, grads, lr, b1=b1, b2=b2, eps=eps,
        weight_decay=weight_decay,
    )
    return tree_where(ok, new_p, params), tree_where(ok, new_s, state)
