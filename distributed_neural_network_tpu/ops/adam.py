"""Adam/AdamW as pure tree ops.

Beyond-reference capability: the reference's only optimizer is
`torch.optim.SGD(lr, momentum)` (`data_parallelism_train.py:187`); a
framework at this scale needs the adaptive family too. Same shape contract
as `ops/sgd.py` - pure functions over parameter pytrees, layout-oblivious
(elementwise), so they run replicated, tensor-sharded, or ZeRO-sharded
(`parallel/zero.py zero_adam_step_sharded`) unchanged. Numerics follow the
standard bias-corrected Adam (Kingma & Ba) with optional decoupled weight
decay (AdamW, Loshchilov & Hutter); parity with optax.adam is pinned by
tests/test_adam.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_adam(params):
    """Zero first/second-moment trees + step counter."""
    return {
        "m": jax.tree.map(jnp.zeros_like, params),
        "v": jax.tree.map(jnp.zeros_like, params),
        "t": jnp.zeros((), jnp.int32),
    }


def adam_step(
    params,
    state,
    grads,
    lr: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
):
    """One (bias-corrected) Adam/AdamW update; returns (params, state)."""
    t = state["t"] + 1
    tf = t.astype(jnp.float32)
    c1 = 1.0 - b1 ** tf
    c2 = 1.0 - b2 ** tf
    m = jax.tree.map(lambda m, g: b1 * m + (1.0 - b1) * g, state["m"], grads)
    v = jax.tree.map(
        lambda v, g: b2 * v + (1.0 - b2) * (g * g), state["v"], grads
    )

    def upd(p, m_, v_):
        step = (m_ / c1) / (jnp.sqrt(v_ / c2) + eps)
        if weight_decay:
            step = step + weight_decay * p
        return p - lr * step

    return jax.tree.map(upd, params, m, v), {"m": m, "v": v, "t": t}
