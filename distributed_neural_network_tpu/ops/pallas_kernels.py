"""Pallas TPU kernels for the model's hot dense path.

The reference's compute is plain ATen ops under torch (`models/model.py:24-27`
runs fc1->fc2->fc3 as three separate GEMMs with separate ReLU kernels and a
round-trip through memory between each). XLA already fuses bias+ReLU into the
GEMM epilogue, but still materializes the (B,120) and (B,84) intermediates in
HBM between the three dots. This module fuses the whole classifier head -

    logits = (relu(relu(x @ W1 + b1) @ W2 + b2)) @ W3 + b3

- into ONE Pallas kernel: all three weight matrices (~59K floats, ~236 KB)
are pinned in VMEM for the kernel's lifetime, the batch streams through in
tiles, and the h1/h2 intermediates never leave VMEM. A custom VJP provides a
matching fused backward kernel (dx plus all six weight/bias grads in one
pass, with cross-tile accumulation in VMEM), so the op is trainable.

Design notes (per the Pallas TPU guide):
- Grid is 1-D over batch tiles; weight/bias blocks use a constant index_map
  so Mosaic keeps them resident in VMEM across grid steps.
- Batch is padded to the tile size on the host-facing wrapper; padded rows
  carry zeros, produce garbage logits that are sliced off, and contribute
  exactly zero to every gradient (their upstream cotangent is zero-padded).
- The backward kernel accumulates dW/db across batch tiles by revisiting the
  same output block each grid step (`@pl.when(i == 0)` zero-init, then `+=`)
  - TPU grid execution is sequential, so this is well-defined.
- All matmuls request `preferred_element_type=float32` so the MXU accumulates
  in f32 regardless of input dtype.
- Off-TPU execution: `interpret=True` runs the kernel code through the Pallas
  interpreter and is how the kernel unit tests exercise it on CPU - but the
  interpreter is not shard_map-compatible (vma typing), so *inside the
  sharded engine* the off-TPU path is the plain-jnp `mlp3_reference` math,
  not the kernel. Mosaic-compiled behavior is only truly covered on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.collectives import vma_union

# batch tile: 8-row sublane alignment, big enough to keep the MXU busy
_TILE_B = 128


def _on_tpu() -> bool:
    try:
        dev = jax.devices()[0]
    except Exception:
        return False
    return dev.platform == "tpu" or "TPU" in getattr(dev, "device_kind", "")


def _interpret_default() -> bool:
    return not _on_tpu()


def _fwd_kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref,
                out_ref, h1_ref=None, h2_ref=None):
    """Forward head; h1/h2 residual outputs only exist on the VJP-fwd
    variant - inference calls write logits alone, keeping the intermediates
    purely in VMEM."""
    h1 = jnp.maximum(
        jnp.dot(x_ref[:], w1_ref[:], preferred_element_type=jnp.float32)
        + b1_ref[:],
        0.0,
    )
    h2 = jnp.maximum(
        jnp.dot(h1, w2_ref[:], preferred_element_type=jnp.float32) + b2_ref[:],
        0.0,
    )
    out_ref[:] = (
        jnp.dot(h2, w3_ref[:], preferred_element_type=jnp.float32) + b3_ref[:]
    )
    if h1_ref is not None:
        h1_ref[:] = h1
        h2_ref[:] = h2


def _bwd_kernel(g_ref, x_ref, h1_ref, h2_ref, w1_ref, w2_ref, w3_ref,
                dx_ref, dw1_ref, db1_ref, dw2_ref, db2_ref, dw3_ref, db3_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        dw1_ref[:] = jnp.zeros_like(dw1_ref)
        db1_ref[:] = jnp.zeros_like(db1_ref)
        dw2_ref[:] = jnp.zeros_like(dw2_ref)
        db2_ref[:] = jnp.zeros_like(db2_ref)
        dw3_ref[:] = jnp.zeros_like(dw3_ref)
        db3_ref[:] = jnp.zeros_like(db3_ref)

    g = g_ref[:]
    h1 = h1_ref[:]
    h2 = h2_ref[:]
    x = x_ref[:]

    dmm = functools.partial(jax.lax.dot_general, preferred_element_type=jnp.float32)
    # dh2 = g @ W3^T, masked by ReLU
    dh2 = dmm(g, w3_ref[:], dimension_numbers=(((1,), (1,)), ((), ())))
    dh2 = jnp.where(h2 > 0, dh2, 0.0)
    dh1 = dmm(dh2, w2_ref[:], dimension_numbers=(((1,), (1,)), ((), ())))
    dh1 = jnp.where(h1 > 0, dh1, 0.0)
    dx_ref[:] = dmm(dh1, w1_ref[:], dimension_numbers=(((1,), (1,)), ((), ())))

    # weight grads: X^T @ dY contractions over the batch tile, accumulated
    # across grid steps
    tmm = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dw3_ref[:] += tmm(h2, g)
    db3_ref[:] += jnp.sum(g, axis=0, keepdims=True)
    dw2_ref[:] += tmm(h1, dh2)
    db2_ref[:] += jnp.sum(dh2, axis=0, keepdims=True)
    dw1_ref[:] += tmm(x, dh1)
    db1_ref[:] += jnp.sum(dh1, axis=0, keepdims=True)


def _out_struct(shape, *vma_sources):
    """ShapeDtypeStruct stamped with the union of the inputs' varying-axes
    (vma) type, required for pallas_call outputs inside jax.shard_map
    (check_vma=True): per-device kernel outputs vary over whatever mesh axes
    the data inputs vary over."""
    vma = vma_union(*vma_sources)
    if vma is None:  # outside shard_map / older API
        return jax.ShapeDtypeStruct(shape, jnp.float32)
    return jax.ShapeDtypeStruct(shape, jnp.float32, vma=vma)


def _pad_batch(a: jax.Array, tile: int):
    b = a.shape[0]
    pad = (-b) % tile
    if pad:
        a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
    return a, b


def _full_spec(shape):
    """Weight/bias block resident across all grid steps."""
    return pl.BlockSpec(shape, lambda i: (0, 0), memory_space=pltpu.VMEM)


def _tile_spec(cols, tile):
    return pl.BlockSpec((tile, cols), lambda i: (i, 0), memory_space=pltpu.VMEM)


def _fwd_call(x, w1, b1, w2, b2, w3, b3, *, tile, interpret, residuals=True):
    xp, b = _pad_batch(x, tile)
    bp = xp.shape[0]
    d_in, d1 = w1.shape
    d2 = w2.shape[1]
    d3 = w3.shape[1]
    out_specs = [_tile_spec(d3, tile)]
    out_shape = [_out_struct((bp, d3), xp, w1, w2, w3)]
    if residuals:
        out_specs += [_tile_spec(d1, tile), _tile_spec(d2, tile)]
        out_shape += [
            _out_struct((bp, d1), xp, w1, w2, w3),
            _out_struct((bp, d2), xp, w1, w2, w3),
        ]
    outs = pl.pallas_call(
        _fwd_kernel,
        grid=(bp // tile,),
        in_specs=[
            _tile_spec(d_in, tile),
            _full_spec(w1.shape),
            _full_spec((1, d1)),
            _full_spec(w2.shape),
            _full_spec((1, d2)),
            _full_spec(w3.shape),
            _full_spec((1, d3)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(xp, w1, b1.reshape(1, -1), w2, b2.reshape(1, -1), w3, b3.reshape(1, -1))
    if residuals:
        out, h1, h2 = outs
        return out[:b], h1, h2
    return outs[0][:b], None, None


def _bwd_call(g, x, h1, h2, w1, w2, w3, *, tile, interpret):
    gp, b = _pad_batch(g, tile)  # zero rows -> zero grad contributions
    xp, _ = _pad_batch(x, tile)
    bp = xp.shape[0]
    d_in, d1 = w1.shape
    d2 = w2.shape[1]
    d3 = w3.shape[1]
    dx, dw1, db1, dw2, db2, dw3, db3 = pl.pallas_call(
        _bwd_kernel,
        grid=(bp // tile,),
        in_specs=[
            _tile_spec(d3, tile),
            _tile_spec(d_in, tile),
            _tile_spec(d1, tile),
            _tile_spec(d2, tile),
            _full_spec(w1.shape),
            _full_spec(w2.shape),
            _full_spec(w3.shape),
        ],
        out_specs=[
            _tile_spec(d_in, tile),
            _full_spec(w1.shape),
            _full_spec((1, d1)),
            _full_spec(w2.shape),
            _full_spec((1, d2)),
            _full_spec(w3.shape),
            _full_spec((1, d3)),
        ],
        out_shape=[
            _out_struct((bp, d_in), gp, xp, w1, w2, w3),
            _out_struct(w1.shape, gp, xp, w1, w2, w3),
            _out_struct((1, d1), gp, xp, w1, w2, w3),
            _out_struct(w2.shape, gp, xp, w1, w2, w3),
            _out_struct((1, d2), gp, xp, w1, w2, w3),
            _out_struct(w3.shape, gp, xp, w1, w2, w3),
            _out_struct((1, d3), gp, xp, w1, w2, w3),
        ],
        interpret=interpret,
    )(gp, xp, h1, h2, w1, w2, w3)
    return dx[:b], dw1, db1[0], dw2, db2[0], dw3, db3[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _fused_mlp3(x, w1, b1, w2, b2, w3, b3, tile, interpret):
    out, _, _ = _fwd_call(
        x, w1, b1, w2, b2, w3, b3, tile=tile, interpret=interpret, residuals=False
    )
    return out


def _fused_mlp3_fwd(x, w1, b1, w2, b2, w3, b3, tile, interpret):
    out, h1, h2 = _fwd_call(x, w1, b1, w2, b2, w3, b3, tile=tile, interpret=interpret)
    return out, (x, h1, h2, w1, w2, w3)


def _fused_mlp3_bwd(tile, interpret, res, g):
    x, h1, h2, w1, w2, w3 = res
    dx, dw1, db1, dw2, db2, dw3, db3 = _bwd_call(
        g, x, h1, h2, w1, w2, w3, tile=tile, interpret=interpret
    )
    return dx, dw1, db1, dw2, db2, dw3, db3


_fused_mlp3.defvjp(_fused_mlp3_fwd, _fused_mlp3_bwd)


def mlp3_reference(x, w1, b1, w2, b2, w3, b3):
    """Plain-jnp math of the fused head - the off-TPU execution path.

    Same computation, natively differentiable; used automatically off-TPU
    because the Pallas HLO interpreter's internal primitives violate
    shard_map's varying-axes (vma) typing when kernel operands mix sharded
    activations with replicated weights. XLA:CPU fuses this fine; the Pallas
    kernel is for the MXU."""
    x = x.astype(jnp.float32)
    h1 = jnp.maximum(x @ w1 + b1, 0.0)
    h2 = jnp.maximum(h1 @ w2 + b2, 0.0)
    return h2 @ w3 + b3


def fused_mlp3(x, w1, b1, w2, b2, w3, b3, *, tile=_TILE_B, interpret=None):
    """relu(relu(x@W1+b1)@W2+b2)@W3+b3 as one Pallas kernel (trainable).

    x: (B, d_in) float32. Returns (B, d_out) float32 logits. All arrays are
    cast to float32 (the kernel's compute and accumulation type).

    `interpret`: None (default) = compiled Mosaic kernel on TPU, jnp
    reference math elsewhere; True = force the Pallas interpreter (kernel
    unit tests; not shard_map-compatible); False = force compilation.
    """
    args = [jnp.asarray(a, jnp.float32) for a in (x, w1, b1, w2, b2, w3, b3)]
    if interpret is None:
        if not _on_tpu():
            return mlp3_reference(*args)
        interpret = False
    return _fused_mlp3(*args, tile, bool(interpret))
