"""SGD with momentum, torch-semantics, as pure tree ops.

Parity: `torch.optim.SGD(lr, momentum)` (`data_parallelism_train.py:187`,
`single_proc_train.py:54`): buf <- mu*buf + grad (no dampening, no nesterov),
p <- p - lr*buf; the first step uses buf = grad, reproduced here by zero
momentum init. Kept as hand-rolled tree ops (rather than optax) because the
reference's observable dynamics include **re-creating the optimizer - and
thus resetting the momentum buffer - every epoch** inside `run_child`
(`data_parallelism_train.py:187`, SURVEY.md section 2 quirks); an explicit
buffer tree makes that reset a one-liner inside the compiled epoch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_momentum(params):
    """Zero momentum buffers - equivalent to a freshly constructed torch SGD."""
    return jax.tree.map(jnp.zeros_like, params)


def sgd_step(params, mom, grads, lr: float, momentum: float):
    """One SGD-momentum update; returns (new_params, new_mom)."""
    mom = jax.tree.map(lambda m, g: momentum * m + g, mom, grads)
    params = jax.tree.map(lambda p, m: p - lr * m, params, mom)
    return params, mom


def guarded_sgd_step(
    params, mom, grads, lr, momentum, *, ok, weight_decay: float = 0.0
):
    """`sgd_step` (+ optional decoupled decay) gated on the traced scalar
    `ok`: when False the entire update - params AND momentum - is dropped
    inside the compiled step (ops/schedule.py tree_where), which is the
    guard's in-jit 'skip' for non-finite gradients (train/guard.py). With
    `ok=True` the result is bitwise identical to the unguarded path."""
    from .schedule import apply_decoupled_weight_decay, tree_where

    new_p, new_m = sgd_step(params, mom, grads, lr, momentum)
    new_p = apply_decoupled_weight_decay(new_p, lr, weight_decay)
    return tree_where(ok, new_p, params), tree_where(ok, new_m, mom)
