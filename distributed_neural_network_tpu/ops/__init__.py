"""Subpackage: ops."""
