"""Loss and metric ops (masked for static-shape padded batches).

Parity: the reference uses `nn.CrossEntropyLoss` (mean reduction) for train
(`data_parallelism_train.py:29,196`) and eval (`:169`), and top-1 accuracy by
argmax (`:173-174`). The weight mask handles padded rows in the final partial
batch (see `data/pipeline.py`) so XLA sees static shapes; for fully valid
batches the math is identical to the reference's.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_cross_entropy(logits, labels, weights):
    """Weighted-mean softmax cross entropy: sum(w*ce)/max(sum(w),1).

    Equals torch CrossEntropyLoss(mean) on batches with all-ones weights.
    """
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ce = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    n = jnp.maximum(weights.sum(), 1.0)
    return (ce * weights).sum() / n


def masked_correct(logits, labels, weights):
    """Count of correct top-1 predictions among valid (weight=1) rows."""
    pred = jnp.argmax(logits, axis=-1)
    return ((pred == labels).astype(jnp.float32) * weights).sum()
