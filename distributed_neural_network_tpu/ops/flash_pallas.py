"""This framework's own flash-attention TPU kernels (fwd + bwd, trainable).

Why not the library kernel (`jax.experimental.pallas.ops.tpu.flash_attention`),
which `ops/flash.py` wrapped through round 3? Two reasons, both structural:

1. **It cannot compose with the meshes.** Under `jax.shard_map` with
   `check_vma=True`, every `pallas_call` output must declare its varying-axes
   (vma) type via `jax.ShapeDtypeStruct(..., vma=...)` - the checker rejects
   untyped outputs outright (jax 0.9 `pallas/pallas_call.py` raises when
   `out_shape.vma is None`), and `check_vma=False` changes gradient semantics
   on non-trivial meshes (shard_map autodiff inserts psums by type). The
   library kernel stamps no vma, so round 3 had to forbid `attn=flash` on any
   real mesh - the framework's fastest attention and its parallelism were
   mutually exclusive (VERDICT r3, weak #4). These kernels stamp every output
   with the union of the inputs' vma, so flash runs under dp x tp shard_map
   with typed gradients.
2. **The backward pass is the measured MFU bottleneck** (r3 honest numbers:
   fwd ~45% MXU efficiency, bwd ~25%; 29.4% MFU end-to-end vs a >=40%
   target), and the library kernel's backward block plumbing is where its
   tuning surface is hardest to reach. Owning the kernel makes the bwd block
   sizes (`FlashBlocks.bq_dkv` etc.) first-class tunables for
   `tools/tune_flash.py`.

Design (per the Pallas TPU guide):
- Layout: the public entry takes this framework's (B, S, H, D) convention,
  collapses to (B*H, S, D), and grids over (B*H, outer blocks, inner
  blocks). Head dim D stays the minor-most axis for MXU-friendly dots.
- **Every kernel is a 3-D grid with VMEM scratch accumulators** (the
  r4 restructure; previously the inner dimension was an in-kernel
  `fori_loop` over slices of full-length VMEM-resident operands, which
  tied VMEM use to S and hid the inner DMAs from the compiler's
  double-buffering). The inner grid axis is "arbitrary" (sequential);
  the carried state (softmax recurrence m/l/acc in the forward, dq / dkv
  partial sums in the backward) lives in VMEM scratch, initialized at
  the first inner step and written to the output block at the last.
  VMEM is now bounded by BLOCK sizes only - independent of S.
- **Causal skipping**: an inner step whose block is entirely on the wrong
  side of the diagonal skips its compute under `pl.when` and clamps its
  index_map to a block that is already resident - the diagonal block in
  fwd/dq (skips are the inner loop's suffix) and block 0 in dkv (skips
  are the prefix) - so skipped steps issue no DMA. The diagonal blocks
  mask with global row/col indices.
- Numerics: dots accumulate in f32 (`preferred_element_type`); the softmax
  recurrence (running max m, denominator l, numerator acc) is carried in
  f32 scratch; p / ds are cast back to the input dtype for the second MXU
  dot (standard flash practice - keeps the MXU on the bf16 fast path).
  The forward saves one f32 logsumexp per row (lse = m + log l) as the
  only softmax residual.
- Backward is the standard two-kernel flash recompute split: the
  dq-kernel's outer blocks are q (inner: k), the dkv-kernel's outer
  blocks are k (inner: q). delta = rowsum(do * o) is precomputed in XLA
  (one fused elementwise pass) and streamed in. Each kernel re-forms p
  from q/k/lse, so the (S, S) score matrix never exists anywhere.
- Per-row residuals (lse, delta) cross the pallas_call boundary
  lane-replicated to (..., 128): Mosaic requires the last two dims of
  every block to be (8, 128)-tileable or full, so a (bq,) row vector is
  not a legal block - it lives as a (bq, 128) broadcast tile (the
  library kernel's MIN_BLOCK_SIZE layout) and kernels read [:, :1].
  Between fwd and bwd only the slim (bh, s) lse is saved; _bwd_call
  re-broadcasts once in XLA.

Reference parity: behaves as `parallel/ring.py attention(q, k, v,
causal=...)` up to blockwise-softmax reassociation; `tests/test_flash_pallas.py`
pins fwd and grad parity (interpret mode on CPU, compiled on TPU) for the
framework the reference never had (its model is a 5-layer CNN -
`models/model.py` - with no attention at all; SURVEY.md section 5.7).
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.collectives import vma_union
from .quant import QUANT_FORMATS, quantize

# jax renamed TPUCompilerParams -> CompilerParams across generations;
# alias so the kernels build (and the CPU interpret tests run) on both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

_NEG_BIG = -1e30  # large-negative mask; avoids -inf NaN propagation
_LANES = 128  # TPU lane width: per-row residuals are lane-replicated
_QEPS = 1e-30  # scale floor for the in-kernel p quantization

# (m,k)x(n,k)->(m,n), (m,k)x(k,n)->(m,n), (k,m)x(k,n)->(m,n)
_NT = (((1,), (1,)), ((), ()))
_NN = (((1,), (0,)), ((), ()))
_TN = (((0,), (0,)), ((), ()))
_dot = functools.partial(jax.lax.dot_general, preferred_element_type=jnp.float32)


@dataclasses.dataclass(frozen=True)
class FlashBlocks:
    """Block sizes for the three kernels; every value is clamped to a
    divisor of S at call time (`resolve`). bq/bk drive the forward;
    (bq_dq, bk_dq) the dq kernel; (bq_dkv, bk_dkv) the dkv kernel - the
    backward pair is the r3-diagnosed MFU lever and what
    `tools/tune_flash.py` sweeps."""

    bq: int = 512
    bk: int = 512
    bq_dq: int = 512
    bk_dq: int = 512
    bq_dkv: int = 512
    bk_dkv: int = 512

    def resolve(self, s: int) -> "FlashBlocks":
        return FlashBlocks(*(_divisor_block(b, s) for b in dataclasses.astuple(self)))


def _divisor_block(b: int, s: int) -> int:
    """Largest divisor of s that is <= b and lane-friendly: prefers
    multiples of 128, falls back to any divisor (tiny test shapes), never
    exceeds s."""
    b = min(b, s)
    for cand in range(b, 127, -1):
        if s % cand == 0 and cand % 128 == 0:
            return cand
    for cand in range(min(b, s), 0, -1):
        if s % cand == 0:
            return cand
    return s


def _struct(shape, dtype, *vma_sources):
    """ShapeDtypeStruct stamped with the union of the sources' vma type -
    what lets these kernels run inside shard_map(check_vma=True)."""
    vma = vma_union(*vma_sources)
    if vma is None:  # outside shard_map / vma-less jax
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _causal_mask(s, qi, bq, kj, bk):
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = kj * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(rows >= cols, s, _NEG_BIG)


# ---------------------------------------------------------------- forward


def _on_diag_or_below(i, bq, j, bk):
    """True when q block i contains any row >= the first col of k block j
    (the block pair carries causal work: max q row (i+1)*bq-1 >= j*bk)."""
    return (i + 1) * bq > j * bk


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc,
                *, bq, bk, scale, causal):
    qi, kj = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_sc[...] = jnp.full(m_sc.shape, _NEG_BIG, m_sc.dtype)
        l_sc[...] = jnp.zeros(l_sc.shape, l_sc.dtype)
        acc_sc[...] = jnp.zeros(acc_sc.shape, acc_sc.dtype)

    def _step():
        q = q_ref[0]  # (bq, D) input dtype
        s = _dot(q, k_ref[0], _NT) * scale  # (bq, bk) f32
        if causal:
            s = _causal_mask(s, qi, bq, kj, bk)
        m = m_sc[...][:, :1]  # (bq, 1) from the lane-replicated scratch
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l_sc[...][:, :1] * alpha + p.sum(-1, keepdims=True)
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)
        acc_sc[...] = acc_sc[...] * alpha + _dot(
            p.astype(v_ref.dtype), v_ref[0], _NN
        )

    if causal:
        pl.when(_on_diag_or_below(qi, bq, kj, bk))(_step)
    else:
        _step()

    @pl.when(kj == n_k - 1)
    def _finish():
        l = jnp.maximum(l_sc[...][:, :1], 1e-30)
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)
        # lane-replicated (bq, 128) write: Mosaic requires the last two
        # block dims to be (8, 128)-tileable, so per-row residuals live
        # broadcast across the lane axis (the library kernel's
        # MIN_BLOCK_SIZE layout); the caller slices lane 0 back off
        lse_ref[0] = jnp.broadcast_to(
            m_sc[...][:, :1] + jnp.log(l), lse_ref.shape[1:]
        )


def _fwd_call(q, k, v, *, blocks, scale, causal, interpret):
    bh, s, d = q.shape
    bq, bk = blocks.bq, blocks.bk
    kernel = functools.partial(
        _fwd_kernel, bq=bq, bk=bk, scale=scale, causal=causal
    )

    def k_index(b, i, j):
        if causal:
            # skipped steps are the SUFFIX of the inner loop (k blocks
            # strictly above the diagonal): re-point at the diagonal
            # block, which the last valid step left resident - no new DMA
            j = jnp.minimum(j, ((i + 1) * bq - 1) // bk)
        return (b, j, 0)

    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), k_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), k_index, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _struct((bh, s, d), q.dtype, q, k, v),
            _struct((bh, s, _LANES), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running max m
            pltpu.VMEM((bq, _LANES), jnp.float32),  # running denom l
            pltpu.VMEM((bq, d), jnp.float32),       # output accumulator
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    # keep only lane 0 as the residual: between fwd and bwd the saved lse
    # is (bh, s), not 128x that (the broadcast back happens in _bwd_call)
    return o, lse[..., 0]


# ----------------------------------------------------- quantized forward
#
# The fp8/int8 fast path (ROADMAP item 3): q/k/v enter the kernel in the
# quantized storage dtype with per-row (per-token) f32 scales riding the
# same lane-replicated (bq, 128) layout as lse, so both MXU dots run in
# low precision:
#
# - QK^T: q-hat @ k-hat-T accumulated wide (int8 -> int32, fp8 -> f32 via
#   preferred_element_type - THE accumulate upcast the shardlint
#   precision lint pins), dequantized by the rank-1 outer product of the
#   row scales BEFORE the softmax max-subtraction, so the online-softmax
#   recurrence (m/l/acc in f32 scratch) is unchanged and per-block scale
#   differences flow through the alpha rescale exactly like score
#   magnitude differences always did.
# - PV: v's per-row scale cannot be factored out of the contraction
#   (sum_j p_ij sv_j v-hat_jd), so it is FOLDED INTO P; the folded p is
#   then quantized per query row with a dynamic in-kernel scale and the
#   second dot runs low-precision too, its contribution dequantized by
#   that one scalar per row.
#
# Backward stays the bf16 kernel pair on the ORIGINAL q/k/v residuals
# (straight-through): training gets full-precision gradients at the
# quantized forward's lse, and the end effect on loss/logits is bounded
# by the bench parity gate (train/measure.py measure_quant_parity), not
# assumed. On hardware, int8/fp8 blocks tile at (32, 128) - the resolved
# block sizes (multiples of 128 at real sequence lengths) satisfy it;
# interpret mode (CPU tests) has no tiling constraint.


def _fwd_quant_kernel(q_ref, k_ref, v_ref, sq_ref, sk_ref, sv_ref,
                      o_ref, lse_ref, m_sc, l_sc, acc_sc,
                      *, bq, bk, scale, causal, fmt):
    qi, kj = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)
    qmax = QUANT_FORMATS[fmt][1]

    @pl.when(kj == 0)
    def _init():
        m_sc[...] = jnp.full(m_sc.shape, _NEG_BIG, m_sc.dtype)
        l_sc[...] = jnp.zeros(l_sc.shape, l_sc.dtype)
        acc_sc[...] = jnp.zeros(acc_sc.shape, acc_sc.dtype)

    def _step():
        q = q_ref[0]  # (bq, D) storage dtype (int8 / fp8)
        k = k_ref[0]  # (bk, D)
        if fmt == "int8":
            s_acc = jax.lax.dot_general(
                q, k, _NT, preferred_element_type=jnp.int32
            ).astype(jnp.float32)
        else:
            s_acc = jax.lax.dot_general(
                q, k, _NT, preferred_element_type=jnp.float32
            )
        sq = sq_ref[0][:, :1]                 # (bq, 1) f32 row scales
        sk = sk_ref[0][:, :1].reshape(1, bk)  # (1, bk)
        s = s_acc * sq * sk * scale
        if causal:
            s = _causal_mask(s, qi, bq, kj, bk)
        m = m_sc[...][:, :1]
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)  # f32, feeds the l recurrence unchanged
        alpha = jnp.exp(m - m_new)
        l_new = l_sc[...][:, :1] * alpha + p.sum(-1, keepdims=True)
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)
        # fold v's per-row scale into p, quantize the folded p per query
        # row, run the PV dot in low precision, dequantize by the row
        # scalar - the per-block scales ride the same alpha rescale the
        # f32 acc always used
        sv = sv_ref[0][:, :1].reshape(1, bk)
        p_f = p * sv
        sp = jnp.maximum(
            jnp.max(jnp.abs(p_f), axis=-1, keepdims=True), _QEPS
        ) / qmax
        p_q = p_f / sp
        if fmt == "int8":
            p_q = jnp.round(p_q)
            pv = jax.lax.dot_general(
                p_q.astype(jnp.int8), v_ref[0], _NN,
                preferred_element_type=jnp.int32,
            ).astype(jnp.float32)
        else:
            pv = jax.lax.dot_general(
                p_q.astype(v_ref.dtype), v_ref[0], _NN,
                preferred_element_type=jnp.float32,
            )
        acc_sc[...] = acc_sc[...] * alpha + pv * sp

    if causal:
        pl.when(_on_diag_or_below(qi, bq, kj, bk))(_step)
    else:
        _step()

    @pl.when(kj == n_k - 1)
    def _finish():
        l = jnp.maximum(l_sc[...][:, :1], 1e-30)
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            m_sc[...][:, :1] + jnp.log(l), lse_ref.shape[1:]
        )


def _fwd_quant_call(q, k, v, *, blocks, scale, causal, interpret, fmt):
    bh, s, d = q.shape
    bq, bk = blocks.bq, blocks.bk
    # per-row symmetric quantization in XLA (one fused pass per operand);
    # scales enter lane-replicated like every per-row residual here
    q_q, sq = quantize(q, fmt)
    k_q, sk = quantize(k, fmt)
    v_q, sv = quantize(v, fmt)
    sq_l = jnp.broadcast_to(sq[..., None], (bh, s, _LANES))
    sk_l = jnp.broadcast_to(sk[..., None], (bh, s, _LANES))
    sv_l = jnp.broadcast_to(sv[..., None], (bh, s, _LANES))
    kernel = functools.partial(
        _fwd_quant_kernel, bq=bq, bk=bk, scale=scale, causal=causal,
        fmt=fmt,
    )

    def k_index(b, i, j):
        if causal:
            j = jnp.minimum(j, ((i + 1) * bq - 1) // bk)
        return (b, j, 0)

    q_index = lambda b, i, j: (b, i, 0)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), q_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), k_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), k_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), q_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, _LANES), k_index,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, _LANES), k_index,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), q_index, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), q_index,
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _struct((bh, s, d), q.dtype, q, k, v),
            _struct((bh, s, _LANES), jnp.float32, q, k, v),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q_q, k_q, v_q, sq_l, sk_l, sv_l)
    return o, lse[..., 0]


# --------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref,
               dq_sc, *, bq, bk, scale, causal):
    qi, kj = pl.program_id(1), pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_sc[...] = jnp.zeros(dq_sc.shape, dq_sc.dtype)

    def _step():
        q = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]  # (bq, 1) f32, lane-replicated block
        dlt = dlt_ref[0][:, :1]
        k_blk = k_ref[0]
        s = _dot(q, k_blk, _NT) * scale
        if causal:
            s = _causal_mask(s, qi, bq, kj, bk)
        p = jnp.exp(s - lse)  # (bq, bk) f32
        dp = _dot(do, v_ref[0], _NT)
        ds = p * (dp - dlt) * scale
        dq_sc[...] = dq_sc[...] + _dot(ds.astype(k_blk.dtype), k_blk, _NN)

    if causal:
        pl.when(_on_diag_or_below(qi, bq, kj, bk))(_step)
    else:
        _step()

    @pl.when(kj == n_k - 1)
    def _finish():
        dq_ref[0] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, bq, bk, scale, causal):
    kj, qi = pl.program_id(1), pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros(dk_sc.shape, dk_sc.dtype)
        dv_sc[...] = jnp.zeros(dv_sc.shape, dv_sc.dtype)

    def _step():
        k = k_ref[0]  # (bk, D)
        q_blk = q_ref[0]
        do_blk = do_ref[0]
        lse_q = lse_ref[0][:, :1]
        dlt_q = dlt_ref[0][:, :1]
        s = _dot(q_blk, k, _NT) * scale  # (bq, bk)
        if causal:
            s = _causal_mask(s, qi, bq, kj, bk)
        p = jnp.exp(s - lse_q)
        dv_sc[...] = dv_sc[...] + _dot(p.astype(do_blk.dtype), do_blk, _TN)
        dp = _dot(do_blk, v_ref[0], _NT)
        ds = p * (dp - dlt_q) * scale
        dk_sc[...] = dk_sc[...] + _dot(ds.astype(q_blk.dtype), q_blk, _TN)

    if causal:
        pl.when(_on_diag_or_below(qi, bq, kj, bk))(_step)
    else:
        _step()

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, *, blocks, scale, causal, interpret):
    bh, s, d = q.shape
    # delta = rowsum(do * o): one fused XLA elementwise+reduce, streamed
    # into both kernels (recomputing it per block would re-read o).
    # Both per-row residuals enter the kernels lane-replicated to
    # (bh, s, 128) - the Mosaic-tileable layout (see _fwd_kernel's note);
    # XLA materializes each broadcast once and both kernels read it.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta_l = jnp.broadcast_to(delta[..., None], (bh, s, _LANES))
    lse_l = jnp.broadcast_to(lse[..., None], (bh, s, _LANES))
    arb = _CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    )

    # dq: grid (bh, q blocks, k inner); k/v/do follow their axes, the
    # causally-skipped inner k blocks clamp to 0 (already resident)
    bq, bk = blocks.bq_dq, blocks.bk_dq

    def k_index_dq(b, i, j):
        if causal:
            # suffix skips: clamp to the resident diagonal block (see
            # _fwd_call's k_index)
            j = jnp.minimum(j, ((i + 1) * bq - 1) // bk)
        return (b, j, 0)

    q_index_dq = lambda b, i, j: (b, i, 0)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=bq, bk=bk, scale=scale,
                          causal=causal),
        grid=(bh, s // bq, s // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), q_index_dq, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), k_index_dq, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), k_index_dq, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, d), q_index_dq, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), q_index_dq,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), q_index_dq,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), q_index_dq,
                               memory_space=pltpu.VMEM),
        out_shape=_struct((bh, s, d), q.dtype, q, k, v, o, do),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=arb,
        interpret=interpret,
    )(q, k, v, do, lse_l, delta_l)

    # dkv: grid (bh, k blocks, q inner); under causality q blocks strictly
    # above the diagonal clamp to block 0 (the library's scheme: one
    # redundant-but-resident DMA instead of a fresh one per skipped step)
    bq, bk = blocks.bq_dkv, blocks.bk_dkv

    def q_index_dkv(b, j, i):
        if causal:
            i = jax.lax.select(_on_diag_or_below(i, bq, j, bk), i, 0)
        return (b, i, 0)

    k_index_dkv = lambda b, j, i: (b, j, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, bk=bk, scale=scale,
                          causal=causal),
        grid=(bh, s // bk, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), q_index_dkv, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), k_index_dkv, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), k_index_dkv, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, d), q_index_dkv, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), q_index_dkv,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), q_index_dkv,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), k_index_dkv, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), k_index_dkv, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _struct((bh, s, d), k.dtype, q, k, v, o, do),
            _struct((bh, s, d), v.dtype, q, k, v, o, do),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=arb,
        interpret=interpret,
    )(q, k, v, do, lse_l, delta_l)
    return dq, dk, dv


# ----------------------------------------------------- custom_vjp wiring


def _any_fwd_call(q, k, v, *, blocks, scale, causal, interpret, quant):
    if quant:
        return _fwd_quant_call(q, k, v, blocks=blocks, scale=scale,
                               causal=causal, interpret=interpret,
                               fmt=quant)
    return _fwd_call(q, k, v, blocks=blocks, scale=scale, causal=causal,
                     interpret=interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, blocks, interpret, quant):
    o, _ = _any_fwd_call(q, k, v, blocks=blocks, scale=scale,
                         causal=causal, interpret=interpret, quant=quant)
    return o


def _flash_fwd(q, k, v, causal, scale, blocks, interpret, quant):
    o, lse = _any_fwd_call(q, k, v, blocks=blocks, scale=scale,
                           causal=causal, interpret=interpret, quant=quant)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, blocks, interpret, quant, res, g):
    # quantized forwards backprop through the bf16 kernels on the
    # ORIGINAL residuals (straight-through; see the quant section note)
    q, k, v, o, lse = res
    return _bwd_call(q, k, v, o, lse, g, blocks=blocks, scale=scale,
                     causal=causal, interpret=interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_mha(q, k, v, *, causal: bool = True, scale=None,
              blocks: FlashBlocks | None = None, interpret: bool = False,
              quant: str | None = None):
    """Flash attention, (B, S, H, D) -> (B, S, H, D), trainable.

    Blockwise-softmax exact attention (up to reassociation): the (S, S)
    score matrix never materializes in forward or backward. vma-typed
    outputs - safe inside shard_map(check_vma=True), so it composes with
    dp x tp meshes (per-device attention is purely local when only batch
    and head axes are sharded; under a sequence axis use
    `parallel/ring.py`). `interpret=True` runs the Pallas interpreter
    (CPU tests); compiled Mosaic otherwise.

    ``quant`` ("int8" | "fp8") switches the forward to the quantized
    kernel: per-row symmetric scales, both MXU dots in the storage
    dtype with wide accumulation, backward unchanged on the bf16
    residuals. Numerics vs the bf16 kernel are bounded by the
    `ops/quant.py` round-trip error (tested; gated end-to-end by the
    bench parity row).
    """
    if quant is not None and quant not in QUANT_FORMATS:
        raise ValueError(
            f"unknown quant format {quant!r}; supported: "
            f"{', '.join(QUANT_FORMATS)} (or None for bf16/f32)"
        )
    b, s, h, d = q.shape
    blocks = (blocks or FlashBlocks()).resolve(s)
    scale = (1.0 / math.sqrt(d)) if scale is None else float(scale)
    qf, kf, vf = (x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
                  for x in (q, k, v))
    o = _flash(qf, kf, vf, causal, scale, blocks, interpret, quant)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)
