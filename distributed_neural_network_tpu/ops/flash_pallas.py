"""This framework's own flash-attention TPU kernels (fwd + bwd, trainable).

Why not the library kernel (`jax.experimental.pallas.ops.tpu.flash_attention`),
which `ops/flash.py` wrapped through round 3? Two reasons, both structural:

1. **It cannot compose with the meshes.** Under `jax.shard_map` with
   `check_vma=True`, every `pallas_call` output must declare its varying-axes
   (vma) type via `jax.ShapeDtypeStruct(..., vma=...)` - the checker rejects
   untyped outputs outright (jax 0.9 `pallas/pallas_call.py` raises when
   `out_shape.vma is None`), and `check_vma=False` changes gradient semantics
   on non-trivial meshes (shard_map autodiff inserts psums by type). The
   library kernel stamps no vma, so round 3 had to forbid `attn=flash` on any
   real mesh - the framework's fastest attention and its parallelism were
   mutually exclusive (VERDICT r3, weak #4). These kernels stamp every output
   with the union of the inputs' vma, so flash runs under dp x tp shard_map
   with typed gradients.
2. **The backward pass is the measured MFU bottleneck** (r3 honest numbers:
   fwd ~45% MXU efficiency, bwd ~25%; 29.4% MFU end-to-end vs a >=40%
   target), and the library kernel's backward block plumbing is where its
   tuning surface is hardest to reach. Owning the kernel makes the bwd block
   sizes (`FlashBlocks.bq_dkv` etc.) first-class tunables for
   `tools/tune_flash.py`.

Design (per the Pallas TPU guide):
- Layout: the public entry takes this framework's (B, S, H, D) convention,
  collapses to (B*H, S, D), and grids over (B*H, blocks). Head dim D stays
  the minor-most axis for MXU-friendly dots.
- The full per-(b,h) K and V live VMEM-resident across a q-block's inner
  loop (constant index_map over the sequence grid axis), so the inner loop
  does no per-iteration HBM traffic. At bf16 that is 2*S*D*2 bytes per
  (b,h) - 0.5 MB at S=2048, 2 MB at S=8192; beyond ~S=16k use sequence
  parallelism (`parallel/ring.py`), which is the mesh-level answer anyway.
- **Causal work skipping is exact, not masked-away**: the inner k-loop bound
  is computed from the q-block's grid index (`lax.fori_loop` with a traced
  bound, the same pattern the library kernel uses at
  `flash_attention.py:363`), so a causal forward does S(S+bk)/2 work, not
  S^2. The diagonal blocks mask with global row/col indices.
- Numerics: dots accumulate in f32 (`preferred_element_type`); the softmax
  recurrence (running max m, denominator l, numerator acc) is carried in
  f32; p / ds are cast back to the input dtype for the second MXU dot
  (standard flash practice - keeps the MXU on the bf16 fast path). The
  forward saves one f32 logsumexp per row (lse = m + log l) as the only
  softmax residual.
- Backward is the standard two-kernel flash recompute split:
  dq-kernel grids over q blocks (inner loop over k), dkv-kernel grids over
  k blocks (inner loop over q, starting at the diagonal under causality).
  delta = rowsum(do * o) is precomputed in XLA (one fused elementwise
  pass) and streamed in. Each kernel re-forms p from q/k/lse, so the
  (S, S) score matrix never exists anywhere in fwd or bwd.
- Per-row residuals (lse, delta) cross the pallas_call boundary
  lane-replicated to (..., 128): Mosaic requires the last two dims of
  every block to be (8, 128)-tileable or full, so a (bq,) row vector is
  not a legal block - it lives as a (bq, 128) broadcast tile (the
  library kernel's MIN_BLOCK_SIZE layout) and kernels read [:, :1].
  Between fwd and bwd only the slim (bh, s) lse is saved; _bwd_call
  re-broadcasts once in XLA. Known cost: the dkv kernel holds both
  residuals full-length in VMEM (2 * S * 128 * 4 bytes - 2 MB at
  S=2048, 8 MB at S=8192), which bounds the practical single-device
  backward at S ~= 6k; past that use sequence parallelism
  (parallel/ring.py), or see the planned 3-D-grid bwd restructure
  (grid over q-blocks instead of an in-kernel fori_loop) that blocks
  the residuals per grid step.

Reference parity: behaves as `parallel/ring.py attention(q, k, v,
causal=...)` up to blockwise-softmax reassociation; `tests/test_flash_pallas.py`
pins fwd and grad parity (interpret mode on CPU, compiled on TPU) for the
framework the reference never had (its model is a 5-layer CNN -
`models/model.py` - with no attention at all; SURVEY.md section 5.7).
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.collectives import vma_union

_NEG_BIG = -1e30  # large-negative mask; avoids -inf NaN propagation
_LANES = 128  # TPU lane width: per-row residuals are lane-replicated

# (m,k)x(n,k)->(m,n), (m,k)x(k,n)->(m,n), (k,m)x(k,n)->(m,n)
_NT = (((1,), (1,)), ((), ()))
_NN = (((1,), (0,)), ((), ()))
_TN = (((0,), (0,)), ((), ()))
_dot = functools.partial(jax.lax.dot_general, preferred_element_type=jnp.float32)


@dataclasses.dataclass(frozen=True)
class FlashBlocks:
    """Block sizes for the three kernels; every value is clamped to a
    divisor of S at call time (`resolve`). bq/bk drive the forward;
    (bq_dq, bk_dq) the dq kernel; (bq_dkv, bk_dkv) the dkv kernel - the
    backward pair is the r3-diagnosed MFU lever and what
    `tools/tune_flash.py` sweeps."""

    bq: int = 512
    bk: int = 512
    bq_dq: int = 512
    bk_dq: int = 512
    bq_dkv: int = 512
    bk_dkv: int = 512

    def resolve(self, s: int) -> "FlashBlocks":
        return FlashBlocks(*(_divisor_block(b, s) for b in dataclasses.astuple(self)))


def _divisor_block(b: int, s: int) -> int:
    """Largest divisor of s that is <= b and lane-friendly: prefers
    multiples of 128, falls back to any divisor (tiny test shapes), never
    exceeds s."""
    b = min(b, s)
    for cand in range(b, 127, -1):
        if s % cand == 0 and cand % 128 == 0:
            return cand
    for cand in range(min(b, s), 0, -1):
        if s % cand == 0:
            return cand
    return s


def _struct(shape, dtype, *vma_sources):
    """ShapeDtypeStruct stamped with the union of the sources' vma type -
    what lets these kernels run inside shard_map(check_vma=True)."""
    vma = vma_union(*vma_sources)
    if vma is None:  # outside shard_map / vma-less jax
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _causal_mask(s, qi, bq, kj, bk):
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = kj * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(rows >= cols, s, _NEG_BIG)


# ---------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, bq, bk, n_k,
                scale, causal):
    qi = pl.program_id(1)
    q = q_ref[0]  # (bq, D) input dtype

    def body(kj, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kj * bk, bk), :]
        v_blk = v_ref[0, pl.ds(kj * bk, bk), :]
        s = _dot(q, k_blk, _NT) * scale  # (bq, bk) f32
        if causal:
            s = _causal_mask(s, qi, bq, kj, bk)
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(-1, keepdims=True)
        acc = acc * alpha + _dot(p.astype(v_blk.dtype), v_blk, _NN)
        return m_new, l, acc

    d = q_ref.shape[-1]
    m0 = jnp.full((bq, 1), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    # causal: q block qi only attends k rows < (qi+1)*bq - skip the rest
    # entirely (traced loop bound), don't mask them away
    n_iter = jnp.minimum((qi * bq + bq + bk - 1) // bk, n_k) if causal else n_k
    m, l, acc = jax.lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # lane-replicated (bq, 128) write: Mosaic requires the last two block
    # dims to be (8, 128)-tileable, so per-row residuals live broadcast
    # across the lane axis (the library kernel's MIN_BLOCK_SIZE layout);
    # the caller slices lane 0 back off
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), (bq, _LANES))


def _fwd_call(q, k, v, *, blocks, scale, causal, interpret):
    bh, s, d = q.shape
    bq, bk = blocks.bq, blocks.bk
    kernel = functools.partial(
        _fwd_kernel, bq=bq, bk=bk, n_k=s // bk, scale=scale, causal=causal
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _struct((bh, s, d), q.dtype, q, k, v),
            _struct((bh, s, _LANES), jnp.float32, q, k, v),
        ],
        interpret=interpret,
    )(q, k, v)
    # keep only lane 0 as the residual: between fwd and bwd the saved lse
    # is (bh, s), not 128x that (the broadcast back happens in _bwd_call)
    return o, lse[..., 0]


# --------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref, *,
               bq, bk, n_k, scale, causal):
    qi = pl.program_id(1)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, :1]  # (bq, 1) f32 from the lane-replicated block
    dlt = dlt_ref[0][:, :1]

    def body(kj, dq_acc):
        k_blk = k_ref[0, pl.ds(kj * bk, bk), :]
        v_blk = v_ref[0, pl.ds(kj * bk, bk), :]
        s = _dot(q, k_blk, _NT) * scale
        if causal:
            s = _causal_mask(s, qi, bq, kj, bk)
        p = jnp.exp(s - lse)  # (bq, bk) f32
        dp = _dot(do, v_blk, _NT)
        ds = p * (dp - dlt) * scale
        return dq_acc + _dot(ds.astype(k_blk.dtype), k_blk, _NN)

    d = q_ref.shape[-1]
    n_iter = jnp.minimum((qi * bq + bq + bk - 1) // bk, n_k) if causal else n_k
    dq = jax.lax.fori_loop(0, n_iter, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                dk_ref, dv_ref, *, bq, bk, n_q, scale, causal):
    kj = pl.program_id(1)
    k = k_ref[0]  # (bk, D)
    v = v_ref[0]

    def body(qi, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[0, pl.ds(qi * bq, bq), :]
        do_blk = do_ref[0, pl.ds(qi * bq, bq), :]
        lse_q = lse_ref[0, pl.ds(qi * bq, bq), :][:, :1]
        dlt_q = dlt_ref[0, pl.ds(qi * bq, bq), :][:, :1]
        s = _dot(q_blk, k, _NT) * scale  # (bq, bk)
        if causal:
            s = _causal_mask(s, qi, bq, kj, bk)
        p = jnp.exp(s - lse_q)
        dv_acc = dv_acc + _dot(p.astype(do_blk.dtype), do_blk, _TN)
        dp = _dot(do_blk, v, _NT)
        ds = p * (dp - dlt_q) * scale
        dk_acc = dk_acc + _dot(ds.astype(q_blk.dtype), q_blk, _TN)
        return dk_acc, dv_acc

    d = q_ref.shape[-1]
    z = jnp.zeros((bk, d), jnp.float32)
    # causal: k block kj only receives gradient from q rows >= kj*bk -
    # start the loop at the diagonal
    start = (kj * bk) // bq if causal else 0
    dk, dv = jax.lax.fori_loop(start, n_q, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, *, blocks, scale, causal, interpret):
    bh, s, d = q.shape
    # delta = rowsum(do * o): one fused XLA elementwise+reduce, streamed
    # into both kernels (recomputing it per block would re-read o).
    # Both per-row residuals enter the kernels lane-replicated to
    # (bh, s, 128) - the Mosaic-tileable layout (see _fwd_kernel's note);
    # XLA materializes each broadcast once and both kernels read it.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta_l = jnp.broadcast_to(delta[..., None], (bh, s, _LANES))
    lse_l = jnp.broadcast_to(lse[..., None], (bh, s, _LANES))

    full = lambda last: pl.BlockSpec((1, s, last), lambda b, i: (b, 0, 0),
                                     memory_space=pltpu.VMEM)
    bq, bk = blocks.bq_dq, blocks.bk_dq
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=bq, bk=bk, n_k=s // bk,
                          scale=scale, causal=causal),
        grid=(bh, s // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            full(d), full(d),
            pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bq, _LANES), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_struct((bh, s, d), q.dtype, q, k, v, o, do),
        interpret=interpret,
    )(q, k, v, do, lse_l, delta_l)

    bq, bk = blocks.bq_dkv, blocks.bk_dkv
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, bk=bk, n_q=s // bq,
                          scale=scale, causal=causal),
        grid=(bh, s // bk),
        in_specs=[
            full(d),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            full(d), full(_LANES), full(_LANES),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            _struct((bh, s, d), k.dtype, q, k, v, o, do),
            _struct((bh, s, d), v.dtype, q, k, v, o, do),
        ],
        interpret=interpret,
    )(q, k, v, do, lse_l, delta_l)
    return dq, dk, dv


# ----------------------------------------------------- custom_vjp wiring


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, blocks, interpret):
    o, _ = _fwd_call(q, k, v, blocks=blocks, scale=scale, causal=causal,
                     interpret=interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, blocks, interpret):
    o, lse = _fwd_call(q, k, v, blocks=blocks, scale=scale, causal=causal,
                       interpret=interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, blocks, interpret, res, g):
    q, k, v, o, lse = res
    return _bwd_call(q, k, v, o, lse, g, blocks=blocks, scale=scale,
                     causal=causal, interpret=interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_mha(q, k, v, *, causal: bool = True, scale=None,
              blocks: FlashBlocks | None = None, interpret: bool = False):
    """Flash attention, (B, S, H, D) -> (B, S, H, D), trainable.

    Blockwise-softmax exact attention (up to reassociation): the (S, S)
    score matrix never materializes in forward or backward. vma-typed
    outputs - safe inside shard_map(check_vma=True), so it composes with
    dp x tp meshes (per-device attention is purely local when only batch
    and head axes are sharded; under a sequence axis use
    `parallel/ring.py`). `interpret=True` runs the Pallas interpreter
    (CPU tests); compiled Mosaic otherwise.
    """
    b, s, h, d = q.shape
    blocks = (blocks or FlashBlocks()).resolve(s)
    scale = (1.0 / math.sqrt(d)) if scale is None else float(scale)
    qf, kf, vf = (x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
                  for x in (q, k, v))
    o = _flash(qf, kf, vf, causal, scale, blocks, interpret)
    return o.reshape(b, h, s, d).transpose(0, 2, 1, 3)
