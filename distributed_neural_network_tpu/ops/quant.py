"""Low-precision quantization primitives: the fp8/int8 fast path's core.

At bf16 the stack's raw-speed levers are exhausted scheduling-side
(53.7% MFU at d1024/L16, 81% at seq 32k - BENCH_MATRIX.json); the next
multiplier on v5e is PRECISION: int8/fp8 operands halve HBM traffic and
double MXU throughput on hardware with native low-precision matmul
units, and an int8 KV cache directly doubles the serving stack's
concurrent-sequence capacity (serve/kv_cache.py). This module is the
shared numerics layer under all of it:

- **quantize / dequantize**: symmetric per-block scaling (one f32 scale
  per ``block`` elements of the quantized axis; ``block=None`` = one
  scale per row, the "per-token" granularity) for two target formats -
  ``int8`` (round-to-nearest onto [-127, 127], zero always exact) and
  ``fp8`` (float8_e4m3fn, scales chosen so the block amax lands at the
  format's max finite 448 - values beyond it would become NaN, not inf,
  so the clamp is load-bearing). An asymmetric (scale + zero-point)
  int8 variant exists for one-sided distributions; the attention/KV
  paths use the symmetric form (K/V are zero-centered projections).
- **roundtrip_error**: the honesty helper - quantize, dequantize, and
  report mae / max abs / relative error so tests and the bench parity
  gate state error BOUNDS instead of vibes.
- **quantized_matmul / quantized_attention**: the XLA reference
  implementations of the quantized kernels (ops/flash_pallas.py's
  ``quant=`` path and ops/decode_pallas.py's int8 stream). Real
  low-precision dots - ``int8 x int8 -> int32`` and ``fp8 x fp8 -> f32``
  via ``preferred_element_type`` - with the accumulate UPCAST to
  f32/bf16 explicit, so the shardlint precision lint can pin it in a
  manifest (analysis/lint.py: a silently-dropped upcast fails
  ``--check``). Off-TPU (CI, laptops) these ARE the quantized path;
  on TPU they are the parity oracle the Pallas kernels are tested
  against.

Numerics contract (what the bench parity gate enforces,
docs/MEASUREMENT.md): per-row symmetric int8 keeps attention-score
round-trip error ~2^-7 relative per operand; fp8-e4m3 ~2^-3. Both are
inside the documented logit-MAE / final-loss-delta tolerances of
``measure_quant_parity`` and the >= 99% per-token top-1 agreement of
the int8 KV serving gate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# quantized formats: name -> (storage dtype, max representable magnitude)
INT8_MAX = 127.0
FP8_MAX = 448.0  # float8_e4m3fn largest finite; beyond it casts to NaN
QUANT_FORMATS = {
    "int8": (jnp.int8, INT8_MAX),
    "fp8": (jnp.float8_e4m3fn, FP8_MAX),
}
# smallest scale: keeps 1/scale finite and an all-zero block exact
_EPS = 1e-30


def quant_dtype(fmt: str):
    """Storage dtype of a quantized format name ('int8' | 'fp8')."""
    _check_fmt(fmt)
    return QUANT_FORMATS[fmt][0]


def _check_fmt(fmt: str) -> None:
    if fmt not in QUANT_FORMATS:
        raise ValueError(
            f"unknown quantized format {fmt!r}; supported: "
            f"{', '.join(QUANT_FORMATS)}"
        )


def _block_view(x, block: int):
    """(..., n) -> (..., n//block, block); n must divide by block."""
    n = x.shape[-1]
    if n % block:
        raise ValueError(
            f"quantization block {block} must divide the quantized axis "
            f"({n})"
        )
    return x.reshape(*x.shape[:-1], n // block, block)


def quantize(x, fmt: str = "int8", *, block: int | None = None):
    """Symmetric quantization of the LAST axis.

    Returns ``(q, scale)``: ``q`` in the format's storage dtype with
    ``x ~= q * scale`` (scale broadcast over each block). ``block=None``
    uses one scale per row (block = whole last axis - the per-token
    granularity the attention paths use); otherwise one f32 scale per
    ``block`` consecutive elements, shaped ``x.shape[:-1] + (n//block,)``.
    Scales are strictly positive (an all-zero block gets scale ~0 and
    exact-zero codes), so dequantization never divides by zero.
    """
    _check_fmt(fmt)
    dtype, qmax = QUANT_FORMATS[fmt]
    xf = x.astype(jnp.float32)
    blocked = block is not None
    if blocked:
        xf = _block_view(xf, block)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, _EPS) / qmax
    q = xf / scale
    if fmt == "int8":
        q = jnp.clip(jnp.round(q), -INT8_MAX, INT8_MAX)
    else:
        # e4m3's max finite is 448: anything beyond saturates to NaN on
        # cast, so clamp first (scale puts amax exactly at 448 already;
        # the clip guards float slop)
        q = jnp.clip(q, -FP8_MAX, FP8_MAX)
    q = q.astype(dtype)
    if blocked:
        q = q.reshape(x.shape)
        scale = scale[..., 0]
    else:
        scale = scale[..., 0]
    return q, scale


def dequantize(q, scale, *, block: int | None = None):
    """Inverse of `quantize`: f32 reconstruction ``q * scale`` with the
    same block layout (``scale`` shaped as `quantize` returned it)."""
    qf = q.astype(jnp.float32)
    if block is None:
        return qf * scale[..., None]
    return (_block_view(qf, block) * scale[..., None]).reshape(q.shape)


def quantize_asymmetric(x, *, block: int | None = None):
    """Asymmetric int8: ``x ~= (q - zero_point) * scale`` with q in
    [0, 255] stored as uint8. One (scale, zero_point) pair per row
    (``block=None``) or per ``block`` elements - the one-sided-
    distribution variant (e.g. post-gelu activations); the attention/KV
    paths use the symmetric form."""
    xf = x.astype(jnp.float32)
    blocked = block is not None
    if blocked:
        xf = _block_view(xf, block)
    lo = jnp.min(xf, axis=-1, keepdims=True)
    hi = jnp.max(xf, axis=-1, keepdims=True)
    scale = jnp.maximum(hi - lo, _EPS) / 255.0
    zp = jnp.round(-lo / scale)
    q = jnp.clip(jnp.round(xf / scale) + zp, 0, 255).astype(jnp.uint8)
    if blocked:
        q = q.reshape(x.shape)
    return q, scale[..., 0], zp[..., 0]


def dequantize_asymmetric(q, scale, zero_point, *, block: int | None = None):
    qf = q.astype(jnp.float32)
    if block is None:
        return (qf - zero_point[..., None]) * scale[..., None]
    v = (_block_view(qf, block) - zero_point[..., None]) * scale[..., None]
    return v.reshape(q.shape)


def roundtrip_error(x, fmt: str = "int8", *, block: int | None = None) -> dict:
    """Quantize -> dequantize -> error report: ``{"mae", "max_abs",
    "rel"}`` (rel = max_abs over the tensor amax). The parity gates and
    tests consume this instead of re-deriving error math."""
    q, scale = quantize(x, fmt, block=block)
    back = dequantize(q, scale, block=block)
    err = jnp.abs(back - x.astype(jnp.float32))
    amax = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32))), _EPS)
    return {
        "mae": float(jnp.mean(err)),
        "max_abs": float(jnp.max(err)),
        "rel": float(jnp.max(err) / amax),
    }


# ------------------------------------------------------- quantized matmul


def _low_precision_dot(a_q, b_q, fmt: str, dn):
    """The quantized MXU dot: int8 x int8 accumulates in int32, fp8 x
    fp8 in f32 (``preferred_element_type``); both return f32. THE
    accumulate upcast lives here - never accumulate in the storage
    dtype (int8 overflows at k > 2 elements; fp8 loses the mantissa)."""
    if fmt == "int8":
        acc = jax.lax.dot_general(
            a_q, b_q, dn, preferred_element_type=jnp.int32
        )
        return acc.astype(jnp.float32)
    return jax.lax.dot_general(
        a_q, b_q, dn, preferred_element_type=jnp.float32
    )


def prequantize_weight(w, fmt: str = "int8"):
    """Quantize a ``(k, n)`` weight ONCE for reuse across many matmuls:
    per-COLUMN symmetric codes stored transposed as ``(n, k)`` plus the
    ``(n,)`` f32 scales - exactly the layout `quantized_matmul` builds
    for its right operand on every call. Leading batch/layer axes pass
    through (a stacked ``(L, k, n)`` weight yields ``(L, n, k)`` codes
    + ``(L, n)`` scales - only the last two axes swap). Serving's
    ``--precision int8-w`` quantizes each weight at engine init and
    feeds the pair back via ``b=(w_q, w_scale)``, so the per-step cost
    drops to quantizing the (tiny) activation rows."""
    _check_fmt(fmt)
    return quantize(jnp.swapaxes(w, -1, -2), fmt)


def quantized_matmul(a, b, fmt: str = "int8", *,
                     weight_only: bool = False):
    """``a (m, k) @ b (k, n)`` through per-row symmetric quantization of
    both operands (b quantized per COLUMN - its contraction axis is
    rows), low-precision dot, f32 dequantized result. ``b`` may also be
    a ``(b_q, b_scale)`` pair from `prequantize_weight` - same numerics,
    weight-side quantization amortized to zero. The XLA reference for
    the Pallas quantized matmul paths, and a usable building block on
    backends without them.

    ``weight_only=True`` is the W8A16 serving recipe: ONLY the weight
    is quantized (codes read from int8 storage, dequantized by the
    per-column scale inside the dot); the activation rows stay at full
    precision. Decode matmuls are bandwidth-bound, so int8 storage
    already buys the 2x HBM win, while skipping activation quantization
    keeps per-token top-1 agreement at the >= 99% gate (the dual-int8
    dot's activation rounding costs ~6% of argmaxes on these model
    scales - fine for training parity tolerances, not for serving's
    token-exactness bar)."""
    _check_fmt(fmt)
    if isinstance(b, tuple):
        b_q, sb = b                               # (n, k), (n,) stored
    else:
        b_q, sb = quantize(b.T, fmt)              # (n, k), (n,)
    if weight_only:
        acc = jax.lax.dot_general(
            a.astype(jnp.float32), b_q.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
        )                                         # (m, n) f32
        return acc * sb[None, :]
    a_q, sa = quantize(a, fmt)                    # (m, k), (m,)
    acc = _low_precision_dot(
        a_q, b_q, fmt, (((1,), (1,)), ((), ()))
    )                                             # (m, n) f32
    return acc * sa[:, None] * sb[None, :]


# ---------------------------------------------------- quantized attention

_NEG_BIG = -1e30


def quantized_attention(q, k, v, *, causal: bool = True, fmt: str = "int8",
                        scale=None):
    """Quantized scaled-dot-product attention, (B, S, H, D) -> same.

    The XLA reference for the quantized flash path
    (`ops/flash_pallas.py flash_mha(quant=...)`) and the off-TPU
    execution path of ``attn_quant`` training (`models/transformer.py`).
    Per-row (per-token, per-head) symmetric scales on q/k/v; QK^T and
    PV both run as true low-precision dots:

    - scores: ``int8/fp8 q-hat @ k-hat`` accumulated wide, dequantized
      by the rank-1 scale outer product, softmaxed in f32 (the standard
      flash numerics);
    - PV: v's per-row scale is FOLDED INTO P (``sum_j p_ij sv_j v-hat_jd
      = sum_j (p_ij sv_j) v-hat_jd``), then the folded P is itself
      quantized per row with a dynamic scale so the second dot is
      low-precision too - exactly the scheme the Pallas kernel carries
      through its online-softmax rescale.

    Gradients flow straight-through jax's autodiff of the same graph
    (round/clip have zero-or-identity derivatives where defined); the
    training parity gate (train/measure.py measure_quant_parity) bounds
    the end effect on loss and logits.
    """
    _check_fmt(fmt)
    b, s, h, d = q.shape
    sc = (1.0 / np.sqrt(d)) if scale is None else float(scale)
    # (B, H, S, D): rows = tokens, the per-row quantized axis is D
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    q_q, sq = quantize(qt, fmt)   # scales (B, H, S)
    k_q, sk = quantize(kt, fmt)
    v_q, sv = quantize(vt, fmt)
    dn = (((3,), (3,)), ((0, 1), (0, 1)))  # contract D, batch (B, H)
    s_int = _low_precision_dot(q_q, k_q, fmt, dn)  # (B, H, S, S) f32
    scores = s_int * sq[..., :, None] * sk[..., None, :] * sc
    if causal:
        rows = jnp.arange(s)[:, None]
        cols = jnp.arange(s)[None, :]
        scores = jnp.where(rows >= cols, scores, _NEG_BIG)
    p = jax.nn.softmax(scores, axis=-1)  # f32
    # fold v's per-row scale into p, then quantize the folded p per row
    p_f = p * sv[..., None, :]
    p_q, sp = quantize(p_f, fmt)
    dn_pv = (((3,), (2,)), ((0, 1), (0, 1)))  # (B,H,S,S) x (B,H,S,D)
    o = _low_precision_dot(p_q, v_q, fmt, dn_pv) * sp[..., None]
    return o.astype(q.dtype).transpose(0, 2, 1, 3)
