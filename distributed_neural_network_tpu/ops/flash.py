"""Flash attention for local (single-device) long-context attention.

The plain local kernel (`parallel/ring.py attention`) materializes the
(B, H, S, S) score matrix, so single-chip long-context is HBM-bound: at
seq 8192 it dominates step time (REPORT.md LM section). This wraps the
Pallas TPU flash-attention kernel that ships with JAX
(`jax.experimental.pallas.ops.tpu.flash_attention`) - the blockwise-softmax
formulation where scores never leave VMEM - behind the framework's
(B, S, H, D) layout convention, falling back to the plain kernel off-TPU
(the Pallas op is Mosaic-only).

Sits alongside the mesh-level answers to long context (ring / Ulysses /
zigzag sequence parallelism, `parallel/ring.py`): flash bounds the
per-chip attention memory at O(S); the seq axis scales beyond it.

Measured reality (v5e-1, 58M-param LM, bf16, this repo's lm_train): at
seq 2048-8192 with head_dim 64 the stock kernel ran 2-5x SLOWER than
XLA's fused attention (which also wins on memory once --remat is on:
45.4k vs 20.8k tokens/s at seq 8192). Exposed as `--attn flash` for
shapes/hardware where the balance differs; verify with your own shapes
before preferring it. Loss trajectories match the plain path exactly.
"""

from __future__ import annotations

import functools
import math

import jax

from ..parallel.ring import attention


@functools.cache
def _flash_available() -> bool:
    if jax.default_backend() != "tpu":
        return False
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401

        return True
    except ImportError:
        return False


def flash_local_attention(q, k, v, *, causal: bool = True):
    """q/k/v (B, S, H, D) -> (B, S, H, D); Pallas flash on TPU, plain
    attention elsewhere. Numerics match `attention` to blockwise-softmax
    reassociation tolerance."""
    if not _flash_available():
        return attention(q, k, v, causal=causal)
    from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention

    d = q.shape[-1]
    out = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=1.0 / math.sqrt(d),
    )
    return out.transpose(0, 2, 1, 3)
