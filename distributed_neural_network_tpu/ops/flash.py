"""Flash attention dispatch for local (per-device) long-context attention.

The plain local kernel (`parallel/ring.py attention`) materializes the
(B, H, S, S) score matrix, so single-chip long-context is HBM-bound: at
seq 8192 it dominates step time (REPORT.md LM section). This module picks
the flash implementation:

- **"own"** (default): this framework's Pallas kernels
  (`ops/flash_pallas.py`) - vma-typed outputs, so they compose with
  dp x tp shard_map under check_vma=True (the library kernel cannot), and
  the backward block sizes are first-class tunables (the r3-diagnosed MFU
  bottleneck).
- **"lib"**: the Pallas kernel that ships with JAX
  (`jax.experimental.pallas.ops.tpu.flash_attention`) - kept as the A/B
  baseline for `tools/tune_flash.py` and as a fallback; single-device
  only (no vma typing).
- Off-TPU both fall back to the plain kernel (Pallas TPU kernels are
  Mosaic-only; the interpreter is not shard_map-compatible).

Select with `DNN_TPU_FLASH_IMPL=own|lib` or the `impl=` argument. Block
sizes: `tools/tune_flash.py` writes `tools/flash_tune_<device>_s<seq>.json`;
`tuned_blocks()` loads the matching file's best own-kernel blocks at call
time (cached), else `FlashBlocks()` defaults.

Block-size tuning status: the round-2 sweep that picked uniform 1024
blocks (and its "2.3x faster than XLA" result) was fenced only with
`block_until_ready`, which is a NO-OP on this backend - those were
dispatch-time artifacts and are RETRACTED (ROADMAP.md measurement-status
note). The honest hard-fenced end-to-end numbers (round 3,
BENCH_MATRIX.json) show flash at 1.25x the XLA+remat path (164.5k vs
132.0k tok/s at d512/L8/seq2048/bf16), with the gap concentrated in the
backward pass. What is solid is that flash never materializes the
(B, H, S, S) score matrix, so the LM can drop --remat (the S^2 buffers
were what forced it).

Sits alongside the mesh-level answers to long context (ring / Ulysses /
zigzag sequence parallelism, `parallel/ring.py`): flash bounds the
per-chip attention memory at O(S); the seq axis scales beyond it.
"""

from __future__ import annotations

import functools
import glob
import json
import math
import os

import jax

from ..parallel.ring import attention
from .flash_pallas import FlashBlocks, flash_mha


@functools.cache
def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


@functools.cache
def _lib_available() -> bool:
    if not _on_tpu():
        return False
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401

        return True
    except ImportError:
        return False


# where tune files live; module-level so tests can point it at a tmp dir
# (tuned_blocks is cached - tests must also cache_clear())
_TUNE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "tools")


@functools.cache
def tuned_blocks(s: int, head_dim: int) -> FlashBlocks:
    """Best own-kernel blocks for (seq s, head_dim) from the tuner's JSON,
    else defaults. A tune file applies only when it was measured on THIS
    device kind at THIS head_dim (mismatched tunings were never measured -
    the guard the retracted r2 sweep lacked), and its seq must equal s or
    divide it (divisor-tuned blocks still tile s; `FlashBlocks.resolve`
    keeps them legal). Exact-seq files win; among divisor files the
    largest seq wins."""
    try:
        dev = jax.devices()[0].device_kind.replace(" ", "_")
    except Exception:
        return FlashBlocks()
    pat = os.path.join(_TUNE_DIR, "flash_tune_*.json")
    best, best_seq = None, -1
    for path in glob.glob(pat):
        try:
            with open(path) as f:
                data = json.load(f)
            own = data.get("best_own")
            shape = data.get("shape", {})
            seq = shape.get("seq", 0)
        except (OSError, json.JSONDecodeError):
            continue
        if (not own or data.get("device") != dev
                or shape.get("head_dim") != head_dim):
            continue
        if seq == s or (seq and s % seq == 0):
            if best_seq != s and (seq == s or seq > best_seq):
                best, best_seq = own, seq
    if not best:
        return FlashBlocks()
    return FlashBlocks(**{k: int(v) for k, v in best.items()
                          if k in FlashBlocks.__dataclass_fields__})


@functools.cache
def _lib_block_sizes(s: int, head_dim: int = 64):
    """Uniform provisional blocks for the LIBRARY kernel, or None for its
    defaults (see module docstring: the 1024-uniform choice came from the
    retracted round-2 sweep; kept because the honest round-3 end-to-end row
    still beat XLA+remat with it). The kernel's `_verify_block` requires
    every block to divide the sequence length, so the size is the largest
    power-of-two divisor of S in [128, 1024]; None when none exists or
    head_dim != 64 (never measured)."""
    if head_dim != 64:
        return None
    for b in (1024, 512, 256, 128):
        if s % b == 0:
            break
    else:
        return None
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    return BlockSizes(
        block_q=b, block_k_major=b, block_k=b, block_b=1,
        block_q_major_dkv=b, block_k_major_dkv=b,
        block_q_dkv=b, block_k_dkv=b,
        block_q_dq=b, block_k_dq=b, block_k_major_dq=b,
    )


def _lib_flash(q, k, v, *, causal: bool):
    from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention

    d = q.shape[-1]
    out = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=1.0 / math.sqrt(d),
        block_sizes=_lib_block_sizes(q.shape[1], d),
    )
    return out.transpose(0, 2, 1, 3)


def flash_local_attention(q, k, v, *, causal: bool = True,
                          impl: str | None = None,
                          quant: str | None = None):
    """q/k/v (B, S, H, D) -> (B, S, H, D); Pallas flash on TPU, plain
    attention elsewhere. Numerics match `attention` to blockwise-softmax
    reassociation tolerance. `impl`: "own" (default; shard_map-composable)
    or "lib" (library kernel, A/B baseline), overridable via
    DNN_TPU_FLASH_IMPL.

    ``quant`` ("int8" | "fp8") selects the low-precision forward
    (`TransformerConfig.attn_quant` / ``--precision``): on TPU the own
    kernel's quantized path (`ops/flash_pallas.py`); off-TPU the XLA
    reference `ops/quant.py quantized_attention` - REAL int8/fp8 dots
    either way, so CPU CI exercises the same quantized numerics the
    chip runs. The library kernel has no quantized path (one more
    reason the kernels are owned - module docstring)."""
    if quant is not None:
        from .quant import QUANT_FORMATS, quantized_attention

        if quant not in QUANT_FORMATS:
            raise ValueError(
                f"unknown quant format {quant!r}; supported: "
                f"{', '.join(QUANT_FORMATS)}"
            )
        if (impl or os.environ.get("DNN_TPU_FLASH_IMPL", "own")) == "lib":
            raise ValueError(
                "the library flash kernel has no quantized path; use "
                "impl='own' (default) for attn quantization"
            )
        if not _on_tpu():
            return quantized_attention(q, k, v, causal=causal, fmt=quant)
        return flash_mha(q, k, v, causal=causal,
                         blocks=tuned_blocks(q.shape[1], q.shape[-1]),
                         quant=quant)
    if not _on_tpu():
        return attention(q, k, v, causal=causal)
    impl = impl or os.environ.get("DNN_TPU_FLASH_IMPL", "own")
    if impl == "lib":
        if not _lib_available():
            raise RuntimeError(
                "flash impl 'lib' requested (DNN_TPU_FLASH_IMPL?) but the "
                "library kernel failed to import on this backend; unset "
                "it to use the own kernel"
            )
        return _lib_flash(q, k, v, causal=causal)
    if impl != "own":
        raise ValueError(f"unknown flash impl {impl!r} (use 'own' or 'lib')")
    return flash_mha(q, k, v, causal=causal,
                     blocks=tuned_blocks(q.shape[1], q.shape[-1]))
