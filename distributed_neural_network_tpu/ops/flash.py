"""Flash attention for local (single-device) long-context attention.

The plain local kernel (`parallel/ring.py attention`) materializes the
(B, H, S, S) score matrix, so single-chip long-context is HBM-bound: at
seq 8192 it dominates step time (REPORT.md LM section). This wraps the
Pallas TPU flash-attention kernel that ships with JAX
(`jax.experimental.pallas.ops.tpu.flash_attention`) - the blockwise-softmax
formulation where scores never leave VMEM - behind the framework's
(B, S, H, D) layout convention, falling back to the plain kernel off-TPU
(the Pallas op is Mosaic-only).

Sits alongside the mesh-level answers to long context (ring / Ulysses /
zigzag sequence parallelism, `parallel/ring.py`): flash bounds the
per-chip attention memory at O(S); the seq axis scales beyond it.

Block-size tuning (round 2, v5e-1, bs16 x seq2048 x 8h x d64, bf16,
chained-dispatch timing so nothing is elided): the kernel's DEFAULT blocks
(block_q 512 / block_k_major 128 / ...) are the reason round 1 measured
flash 2-5x slower than XLA - defaults give fwd 18.3 ms / fwd+bwd 26.8 ms
vs XLA's 13.3 / 22.2 ms. With uniform 1024 blocks the same kernel runs
fwd 8.4 ms / fwd+bwd 9.5 ms - 2.3x FASTER than XLA fused attention - and,
unlike the XLA path, never materializes the (B, H, S, S) score matrix, so
the LM can drop --remat (the S^2 buffers were what forced it) and skip
the whole forward recompute. `_block_sizes` applies that tuning, clamped
to the sequence length. Loss trajectories match the plain path exactly.
"""

from __future__ import annotations

import functools
import math

import jax

from ..parallel.ring import attention


@functools.cache
def _flash_available() -> bool:
    if jax.default_backend() != "tpu":
        return False
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401

        return True
    except ImportError:
        return False


@functools.cache
def _block_sizes(s: int, head_dim: int = 64):
    """Uniform tuned blocks for the flash kernel, or None for library defaults.

    The 1024-uniform tuning was measured at head_dim 64 on v5e among
    {defaults, 256, 512, 1024, 2048}^2 combinations (512 wins fwd-only but
    loses the round trip). The kernel's `_verify_block` requires every block
    to divide the sequence length, so the tuned size is the largest
    power-of-two divisor of S in [128, 1024]; when none exists (S < 128 or
    S not 128-aligned, e.g. the CLI default seq 64) or head_dim != 64
    (where the tuning was never measured), return None and let the kernel
    pick its own verified defaults instead of raising."""
    if head_dim != 64:
        return None
    for b in (1024, 512, 256, 128):
        if s % b == 0:
            break
    else:
        return None
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    return BlockSizes(
        block_q=b, block_k_major=b, block_k=b, block_b=1,
        block_q_major_dkv=b, block_k_major_dkv=b,
        block_q_dkv=b, block_k_dkv=b,
        block_q_dq=b, block_k_dq=b, block_k_major_dq=b,
    )


def flash_local_attention(q, k, v, *, causal: bool = True):
    """q/k/v (B, S, H, D) -> (B, S, H, D); Pallas flash on TPU, plain
    attention elsewhere. Numerics match `attention` to blockwise-softmax
    reassociation tolerance."""
    if not _flash_available():
        return attention(q, k, v, causal=causal)
    from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention

    d = q.shape[-1]
    out = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=1.0 / math.sqrt(d),
        block_sizes=_block_sizes(q.shape[1], d),
    )
    return out.transpose(0, 2, 1, 3)
