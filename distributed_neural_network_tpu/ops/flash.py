"""Flash attention for local (single-device) long-context attention.

The plain local kernel (`parallel/ring.py attention`) materializes the
(B, H, S, S) score matrix, so single-chip long-context is HBM-bound: at
seq 8192 it dominates step time (REPORT.md LM section). This wraps the
Pallas TPU flash-attention kernel that ships with JAX
(`jax.experimental.pallas.ops.tpu.flash_attention`) - the blockwise-softmax
formulation where scores never leave VMEM - behind the framework's
(B, S, H, D) layout convention, falling back to the plain kernel off-TPU
(the Pallas op is Mosaic-only).

Sits alongside the mesh-level answers to long context (ring / Ulysses /
zigzag sequence parallelism, `parallel/ring.py`): flash bounds the
per-chip attention memory at O(S); the seq axis scales beyond it.

Block-size tuning status: the round-2 sweep that picked uniform 1024
blocks (and its "2.3x faster than XLA" result) was fenced only with
`block_until_ready`, which is a NO-OP on this backend - those were
dispatch-time artifacts and are RETRACTED (ROADMAP.md measurement-status
note). The honest hard-fenced end-to-end numbers (round 3,
BENCH_MATRIX.json) show flash at 1.25x the XLA+remat path (164.5k vs
132.0k tok/s at d512/L8/seq2048/bf16), with the gap concentrated in the
backward pass. The uniform blocks in `_block_sizes` are therefore a
PROVISIONAL choice pending a hard-fenced re-tune
(`tools/tune_flash.py`); what is solid is that flash never materializes
the (B, H, S, S) score matrix, so the LM can drop --remat (the S^2
buffers were what forced it). Loss trajectories match the plain path
exactly.
"""

from __future__ import annotations

import functools
import math

import jax

from ..parallel.ring import attention


@functools.cache
def _flash_available() -> bool:
    if jax.default_backend() != "tpu":
        return False
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention  # noqa: F401

        return True
    except ImportError:
        return False


@functools.cache
def _block_sizes(s: int, head_dim: int = 64):
    """Uniform provisional blocks for the flash kernel, or None for defaults.

    The 1024-uniform choice came from the retracted round-2 dispatch-time
    sweep (see module docstring) and awaits hard-fenced re-validation via
    `tools/tune_flash.py` - it is kept because the honest round-3
    end-to-end row still beat XLA+remat with these blocks, but the
    per-block numbers behind it bound nothing.
    The kernel's `_verify_block` requires every block
    to divide the sequence length, so the tuned size is the largest
    power-of-two divisor of S in [128, 1024]; when none exists (S < 128 or
    S not 128-aligned, e.g. the CLI default seq 64) or head_dim != 64
    (where the tuning was never measured), return None and let the kernel
    pick its own verified defaults instead of raising."""
    if head_dim != 64:
        return None
    for b in (1024, 512, 256, 128):
        if s % b == 0:
            break
    else:
        return None
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    return BlockSizes(
        block_q=b, block_k_major=b, block_k=b, block_b=1,
        block_q_major_dkv=b, block_k_major_dkv=b,
        block_q_dkv=b, block_k_dkv=b,
        block_q_dq=b, block_k_dq=b, block_k_major_dq=b,
    )


def flash_local_attention(q, k, v, *, causal: bool = True):
    """q/k/v (B, S, H, D) -> (B, S, H, D); Pallas flash on TPU, plain
    attention elsewhere. Numerics match `attention` to blockwise-softmax
    reassociation tolerance."""
    if not _flash_available():
        return attention(q, k, v, causal=causal)
    from jax.experimental.pallas.ops.tpu.flash_attention import flash_attention

    d = q.shape[-1]
    out = flash_attention(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=1.0 / math.sqrt(d),
        block_sizes=_block_sizes(q.shape[1], d),
    )
    return out.transpose(0, 2, 1, 3)
