"""Learning-rate schedules and gradient transforms as pure ops.

Beyond-reference capability: the reference trains at a fixed lr with no
clipping (`data_parallelism_train.py:187` - bare torch SGD); a framework
carrying the transformer family needs the standard loop trio - warmup +
decay schedules, global-norm clipping, gradient accumulation. All are
pure functions over scalars/pytrees so they compose with any optimizer
(`ops/sgd.py`, `ops/adam.py`, the ZeRO variants) under jit/shard_map.

TPU notes: schedules take the step as a traced scalar (no Python-side
recompile per step); `global_norm` is sharding-aware - pass the leaf ->
PartitionSpec tree and the mesh axes, and leaves sharded over a mesh axis
get their squared-sum psummed over exactly the axes they are split on
(replicated leaves hold identical full gradients after shard_map's typed
autodiff, so they contribute locally). That makes clip-by-global-norm
produce the same scale factor on every device of a dp x sp x tp mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def warmup_cosine(
    step,
    *,
    base_lr: float,
    total_steps: int,
    warmup_steps: int = 0,
    min_lr_frac: float = 0.0,
):
    """lr at `step` (traced or int): linear warmup then cosine decay.

    Warmup ramps 0 -> base_lr over `warmup_steps` (lr at step 0 is
    base_lr/warmup_steps, reaching base_lr at step == warmup_steps); the
    remaining total_steps - warmup_steps decay by half-cosine to
    base_lr * min_lr_frac and stay there.
    """
    if total_steps <= 0:
        raise ValueError(f"total_steps must be > 0, got {total_steps}")
    if not 0 <= warmup_steps <= total_steps:
        raise ValueError(
            f"warmup_steps ({warmup_steps}) must be in [0, total_steps "
            f"({total_steps})]"
        )
    t = jnp.asarray(step, jnp.float32)
    warm = jnp.float32(max(warmup_steps, 1))
    ramp = jnp.minimum((t + 1.0) / warm, 1.0)
    span = jnp.float32(max(total_steps - warmup_steps, 1))
    frac = jnp.clip((t - warmup_steps) / span, 0.0, 1.0)
    cos = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
    decay = min_lr_frac + (1.0 - min_lr_frac) * cos
    return base_lr * jnp.where(t < warmup_steps, ramp, decay)


def constant_lr(step, *, base_lr: float, **_):
    """Fixed lr (the reference's behavior); same signature as the others."""
    return jnp.asarray(base_lr, jnp.float32) + 0.0 * jnp.asarray(
        step, jnp.float32
    )


SCHEDULES = {"constant": constant_lr, "cosine": warmup_cosine}


def global_norm(grads, *, specs=None, axes=()):
    """Global L2 norm of a gradient pytree, sharding-aware.

    Single-device (specs=None or axes=()): plain sqrt(sum of squares).
    Under shard_map: `specs` is the leaf-aligned PartitionSpec tree and
    `axes` the mesh axis names in scope; each leaf's squared sum is
    psummed over the axes its spec shards it on (tensor-parallel leaves),
    while replicated leaves - whose gradient shard_map's typed autodiff
    already psummed - contribute their local (= full) value once.
    """
    leaves = jax.tree.leaves(grads)
    if specs is None or not axes:
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        return jnp.sqrt(sq)
    spec_leaves = jax.tree.leaves(
        specs, is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec)
    )
    assert len(spec_leaves) == len(leaves), (len(spec_leaves), len(leaves))
    total = jnp.float32(0.0)
    axes = set(axes)
    for g, spec in zip(leaves, spec_leaves):
        sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
        shard_axes = tuple(
            a
            for entry in spec
            if entry is not None
            for a in ((entry,) if isinstance(entry, str) else tuple(entry))
            if a in axes
        )
        if shard_axes:
            sq = jax.lax.psum(sq, shard_axes)
        total = total + sq
    return jnp.sqrt(total)


def per_leaf_sq_norms(tree, *, specs=None, axes=()):
    """Per-leaf squared L2 norms of a pytree, sharding-aware.

    Returns a tree congruent to `tree` whose leaves are f32 scalars: the
    GLOBAL squared norm of each leaf. Same reduction logic as
    `global_norm` (each leaf's local squared sum is psummed over exactly
    the mesh axes its spec shards it on; replicated leaves - whose value
    typed autodiff already psummed - contribute their local copy once),
    but WITHOUT collapsing across leaves: the per-layer resolution is the
    point (train/dynamics.py buckets these by the `/`-joined tree paths
    parallel/rules.py `named_leaves` yields). Summing the returned leaves
    and sqrt-ing reproduces `global_norm` up to float reassociation.
    """
    leaves, treedef = jax.tree.flatten(tree)
    if specs is None or not axes:
        sq_leaves = [
            jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves
        ]
        return jax.tree.unflatten(treedef, sq_leaves)
    spec_leaves = jax.tree.leaves(
        specs, is_leaf=lambda s: isinstance(s, jax.sharding.PartitionSpec)
    )
    assert len(spec_leaves) == len(leaves), (len(spec_leaves), len(leaves))
    axes = set(axes)
    sq_leaves = []
    for g, spec in zip(leaves, spec_leaves):
        sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
        shard_axes = tuple(
            a
            for entry in spec
            if entry is not None
            for a in ((entry,) if isinstance(entry, str) else tuple(entry))
            if a in axes
        )
        if shard_axes:
            sq = jax.lax.psum(sq, shard_axes)
        sq_leaves.append(sq)
    return jax.tree.unflatten(treedef, sq_leaves)


def clip_by_global_norm(grads, max_norm: float, *, specs=None, axes=()):
    """Scale `grads` so the global norm is at most `max_norm`.

    Returns (clipped_grads, pre_clip_norm). The scale factor is computed
    from the sharding-aware `global_norm`, so every device applies the
    identical factor and tensor-sharded layouts stay consistent.
    """
    norm = global_norm(grads, specs=specs, axes=axes)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads), norm


def apply_decoupled_weight_decay(params, lr_t, weight_decay: float):
    """AdamW-style decay applied AFTER the optimizer update: p -= lr*wd*p.

    Shared by the mesh sgd, ZeRO-sgd, and pipeline paths so a future
    refinement (e.g. excluding norm/bias leaves) lands everywhere at
    once; Adam variants apply decay inside `adam_leaf_update` instead.
    """
    if not weight_decay:
        return params
    return jax.tree.map(lambda p: p - lr_t * weight_decay * p, params)


def health_bundle(loss, grad_norm):
    """O(1) in-jit health signals for the guard layer (train/guard.py).

    Both inputs are scalars the step already computed - the loss and the
    global gradient norm (`clip_by_global_norm` returns it; unclipped
    guarded steps call `global_norm` once). The all-finite flag is DERIVED
    from them: a NaN/Inf anywhere in the gradient tree makes the global
    norm non-finite (squares and sums propagate it), so no second pass
    over the parameters is needed. All three values are replicated across
    the mesh (loss and the sharding-aware norm already are), so every
    device - and the host policy loop - sees the same verdict.
    """
    loss32 = jnp.asarray(loss, jnp.float32)
    norm32 = jnp.asarray(grad_norm, jnp.float32)
    return {
        "loss": loss32,
        "grad_norm": norm32,
        "all_finite": jnp.isfinite(loss32) & jnp.isfinite(norm32),
    }


def tree_where(ok, new_tree, old_tree):
    """Per-leaf `jnp.where(ok, new, old)` on a traced scalar predicate.

    The guard's in-jit 'skip': when `ok` is False the whole update
    (params AND optimizer state, including Adam's step counter) passes
    through unchanged - one select per leaf, no host round-trip, no
    recompile, so a NaN'd step costs one wasted fwd/bwd and nothing else.
    """
    return jax.tree.map(
        lambda a, b: jnp.where(ok, a, b), new_tree, old_tree
    )


def accumulate_fwd_bwd(fwd_bwd_one, accum_steps: int, *, sq_norm_fn=None):
    """Wrap a per-micro-batch (params, tokens, targets) -> (loss, grads)
    into a k-step gradient-accumulation scan over B/k-row slices.

    Shared by the mesh path (train/lm.py) and the pipeline path
    (parallel/pipeline.py): k-times the effective batch in one
    activation-memory footprint. The accumulator is seeded with
    micro-batch 0 OUTSIDE the scan: its (loss, grads) carry exactly the
    vma types the scan carry needs, with no per-leaf guessing about
    which mesh axes autodiff varies over. Call inside shard_map; the
    averaged (loss, grads) match one k-times-larger batch up to float
    reassociation.

    sq_norm_fn (optional, requires accum_steps >= 2): a grads -> f32
    scalar squared-norm reducer. When set, the wrapped fwd_bwd returns a
    THIRD output: the mean over microbatches of sq_norm_fn applied to
    each PER-MICROBATCH gradient - i.e. E[|g_small|^2] at batch B/k, the
    small-batch half of the gradient-noise-scale estimator
    (train/dynamics.py gns_estimate; the accumulated |g_big|^2 comes from
    the averaged grads the caller already has). Inside the scan the
    per-microbatch grads are the fully synced gradients (typed autodiff
    psums after each backward on the end schedule), so the reducer sees
    global norms. The default (sq_norm_fn=None) path is byte-identical
    to before.
    """
    if accum_steps == 1:
        if sq_norm_fn is not None:
            raise ValueError(
                "sq_norm_fn needs accum_steps >= 2: at k=1 the micro- and "
                "accumulated gradients coincide and the noise-scale "
                "estimator's denominator vanishes"
            )
        return fwd_bwd_one

    def fwd_bwd(params, tokens, targets):
        b_local = tokens.shape[0]
        if b_local % accum_steps:
            raise ValueError(
                f"per-device batch ({b_local}) must divide by accum_steps "
                f"({accum_steps})"
            )
        mb = b_local // accum_steps
        tok_k = tokens.reshape(accum_steps, mb, -1)
        tgt_k = targets.reshape(accum_steps, mb, -1)
        loss0, g0 = fwd_bwd_one(params, tok_k[0], tgt_k[0])
        if sq_norm_fn is None:
            first = (loss0, g0)

            def body(carry, tt):
                loss_acc, grads_acc = carry
                loss, grads = fwd_bwd_one(params, *tt)
                return (
                    loss_acc + loss,
                    jax.tree.map(jnp.add, grads_acc, grads),
                ), None

            (loss_sum, grads_sum), _ = jax.lax.scan(
                body, first, (tok_k[1:], tgt_k[1:])
            )
            k = jnp.float32(accum_steps)
            return loss_sum / k, jax.tree.map(lambda g: g / k, grads_sum)

        first = (loss0, g0, sq_norm_fn(g0))

        def body_sq(carry, tt):
            loss_acc, grads_acc, sq_acc = carry
            loss, grads = fwd_bwd_one(params, *tt)
            return (
                loss_acc + loss,
                jax.tree.map(jnp.add, grads_acc, grads),
                sq_acc + sq_norm_fn(grads),
            ), None

        (loss_sum, grads_sum, sq_sum), _ = jax.lax.scan(
            body_sq, first, (tok_k[1:], tgt_k[1:])
        )
        k = jnp.float32(accum_steps)
        return (
            loss_sum / k,
            jax.tree.map(lambda g: g / k, grads_sum),
            sq_sum / k,
        )

    return fwd_bwd


def accumulate_fwd_bwd_overlap(
    fwd_bwd_one, accum_steps: int, *, reduce_fn, finalize_fn
):
    """Gradient accumulation with the sync collective INSIDE the scan.

    `accumulate_fwd_bwd` is compute-then-communicate: the carry holds the
    full local gradient tree and the cross-device reduction fires once,
    after the last microbatch's backward, so the interconnect idles for
    the entire scan. This variant moves the reduction into the scan body:
    each microbatch's gradients are immediately handed to `reduce_fn`
    (a bucketed psum for plain DP, a bucketed reduce-scatter for the ZeRO
    shard-carry - parallel/collectives.py) and the carry accumulates the
    REDUCED form, which XLA's latency-hiding scheduler can overlap with
    the next microbatch's backward - and which for reduce-scatter is
    1/N-th the accumulator memory. After the scan, `finalize_fn` maps the
    averaged reduced carry back to a full gradient tree (identity for
    psum buckets, the invariant-typed bucket all-gather for shards).

    fwd_bwd_one(params, tokens, targets) -> (loss, grads) with grads
    LOCAL (the caller suppresses the implicit typed-autodiff psum by
    differentiating w.r.t. device-varying params - see train/lm.py);
    reduce_fn(grads) -> reduced (any fixed pytree of arrays);
    finalize_fn(reduced_avg) -> grads tree. The schedule matches the
    end-sync result up to float reassociation. Requires accum_steps >= 2:
    at k=1 there is nothing to overlap and callers keep the end schedule
    (whose result is then bitwise identical by construction).
    """
    if accum_steps < 2:
        raise ValueError(
            f"overlap accumulation needs accum_steps >= 2, got "
            f"{accum_steps} (at k=1 the schedules coincide - use the end "
            "path, which is bitwise identical)"
        )

    def fwd_bwd(params, tokens, targets):
        b_local = tokens.shape[0]
        if b_local % accum_steps:
            raise ValueError(
                f"per-device batch ({b_local}) must divide by accum_steps "
                f"({accum_steps})"
            )
        mb = b_local // accum_steps
        tok_k = tokens.reshape(accum_steps, mb, -1)
        tgt_k = targets.reshape(accum_steps, mb, -1)
        loss0, g0 = fwd_bwd_one(params, tok_k[0], tgt_k[0])
        first = (loss0, reduce_fn(g0))

        def body(carry, tt):
            loss_acc, red_acc = carry
            loss, grads = fwd_bwd_one(params, *tt)
            red = reduce_fn(grads)
            return (
                loss_acc + loss,
                jax.tree.map(jnp.add, red_acc, red),
            ), None

        (loss_sum, red_sum), _ = jax.lax.scan(
            body, first, (tok_k[1:], tgt_k[1:])
        )
        k = jnp.float32(accum_steps)
        red_avg = jax.tree.map(lambda x: (x / k).astype(x.dtype), red_sum)
        return loss_sum / k, finalize_fn(red_avg)

    return fwd_bwd


GRAD_SYNCS = ("end", "overlap")


def make_ema_update(decay: float):
    """Compiled EMA tracker: ema <- decay*ema + (1-decay)*params.

    Kept OUTSIDE the train step on purpose: the EMA is eval-side state
    (evaluating/serving with averaged weights), so tracking it separately
    leaves the optimizer state, checkpoints, and the donated step
    signature untouched - call it after each step (or every k steps,
    adjusting decay to decay**k for the same horizon).
    """
    if not 0.0 < decay < 1.0:
        raise ValueError(f"ema decay must be in (0, 1), got {decay}")

    def update(ema, params):
        return jax.tree.map(
            lambda e, p: decay * e + (1.0 - decay) * p, ema, params
        )

    return jax.jit(update)
