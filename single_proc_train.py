#!/usr/bin/env python
"""Single-device CIFAR-10 baseline - TPU-native entry point.

Capability parity with the reference `single_proc_train.py` (no argparse
there; constants bs=4, SGD lr=0.001 momentum=0.9, 15 epochs at `:35,54,57`,
per-epoch test eval `:84-105`). Those constants are this script's flag
defaults, so running it bare reproduces the reference configuration; unlike
the reference, every knob is a typed flag.

The training loop itself is the shared engine in "single" regime: a mesh of
one device, the whole dataset resident in HBM, each epoch one compiled
`lax.scan` (see distributed_neural_network_tpu/train/engine.py).
"""

import argparse

from distributed_neural_network_tpu.train.cli import add_common_flags, run_training

if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    # reference constants as defaults: single_proc_train.py:35 (bs=4), :54
    # (lr/momentum), :57 (15 epochs)
    add_common_flags(parser, epochs=15, batch_size=4)
    args = parser.parse_args()
    run_training(args, "single")
