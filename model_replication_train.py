#!/usr/bin/env python
"""Model-replication training - TPU-native entry point.

Capability parity with the reference `model_replication_train.py`: every
worker trains on the FULL dataset (`:39-47`), parameters are averaged at each
epoch boundary (`:134-136`), parent evaluates (`:148`). Reference flags
`--lr --momentum --batch-size --epochs` (`:153-159`, defaults epochs=10)
are preserved and typed; `--nb-proc` is added (the reference took the world
size from mpiexec - here it is the mesh size).

TPU-native mapping: full-dataset replication is `jax.device_put` with a
replicated NamedSharding (the analog of `jax.device_put_replicated`), each
device runs an independent per-epoch shuffle, and the epoch-edge averaging is
a fused pmean collective over the mesh - no parent process, no pickle.
"""

import argparse

from distributed_neural_network_tpu.train.cli import (
    add_common_flags,
    add_distributed_flags,
    run_training,
)

if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    # reference defaults: model_replication_train.py:153-159 (epochs=10, bs=16)
    add_common_flags(parser, epochs=10, batch_size=16)
    add_distributed_flags(parser)
    args = parser.parse_args()
    run_training(args, "replication")
