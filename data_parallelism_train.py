#!/usr/bin/env python
"""Data-parallel training - TPU-native entry point (the flagship script).

Capability parity with the reference `data_parallelism_train.py`: disjoint
contiguous 1/N shards per worker (`:49-53,66-79`), local SGD per epoch with
per-epoch momentum reset (`:187-203`), epoch-edge parameter averaging
(`:238-244`), per-epoch eval (`:157-183`), fault simulation (`:41-46`), phase
timing (`:33-37`), and the exact `log/bs{bs}_log_epochs{E}_proc{N}_*` phase
logs (`:103-104,143-152`). Reference flags `--lr --momentum --batch-size
--epochs --nb-proc --failure-probability --failure-duration` (`:259-271`)
are preserved and typed.

TPU-native mapping: `--nb-proc N` builds an N-device mesh; the N local-SGD
epochs run as one `shard_map`'d `lax.scan` each; the parent's send/recv/
average star becomes a fault-masked pmean on ICI; eval is sharded across the
mesh instead of serial on a parent. All N devices train (the reference left
rank 0 idle - use --reference-compat for N-1-worker semantics).
"""

import argparse

from distributed_neural_network_tpu.train.cli import (
    add_common_flags,
    add_distributed_flags,
    run_training,
)

if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    # reference defaults: data_parallelism_train.py:259-271 (bs=16, epochs=25,
    # nb-proc=4, failure prob/duration 0.0)
    add_common_flags(parser, epochs=25, batch_size=16)
    add_distributed_flags(parser)
    args = parser.parse_args()
    run_training(args, "data_parallel")
