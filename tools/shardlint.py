#!/usr/bin/env python
"""shardlint CLI: static sharding/collective/donation analysis on CPU.

Abstractly traces the canonical train-step configs (no execution, no TPU;
distributed_neural_network_tpu/analysis/) and

- lints PartitionSpecs, donation, ZeRO replication leaks, and precision,
- writes or checks the expected-collectives manifests
  (distributed_neural_network_tpu/analysis/manifests/*.json).

Usage:
  python tools/shardlint.py --list
  python tools/shardlint.py --all --check          # the CI gate
  python tools/shardlint.py --config lm_zero_overlap --write-manifest
  python tools/shardlint.py --config lm_dp,lm_tp   # comma lists work
  python tools/shardlint.py --explain --config lm_zero_overlap
                                                   # per-site provenance
  python tools/shardlint.py --all --write-manifest # after an intentional
                                                   # collective change

Exit codes: 0 conforming; 1 lint errors or manifest mismatch; 2 a config
could not be built/traced or an unknown --config name (the known list is
printed). See docs/STATIC_ANALYSIS.md.
"""

import argparse
import os
import sys


def _force_cpu_mesh():
    """8 virtual CPU devices, set BEFORE jax import (the repo-standard
    test mesh - tests/conftest.py does the same for pytest)."""
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "jax" in sys.modules:
        import jax

        try:  # re-assert against site hooks that pre-import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--config", action="append", default=[],
        help="config name(s): repeatable and/or comma-separated "
        "(--config a,b); see --list",
    )
    ap.add_argument("--all", action="store_true", help="every canonical config")
    ap.add_argument("--list", action="store_true", help="list configs and exit")
    ap.add_argument(
        "--write-manifest", action="store_true",
        help="regenerate the expected-collectives manifest(s)",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="diff fresh traces against the checked-in manifest(s)",
    )
    ap.add_argument(
        "--explain", action="store_true",
        help="per-collective-site provenance table (op, axes, bytes/call, "
        "static multiplicity, dynamic flag, enclosing jaxpr path) instead "
        "of the merged per-op summary",
    )
    ap.add_argument(
        "--manifest-dir", default=None,
        help="manifest directory (default: the in-package analysis/manifests)",
    )
    ap.add_argument(
        "-q", "--quiet", action="store_true",
        help="findings and verdicts only (no per-collective breakdown)",
    )
    ap.add_argument(
        "--jobs", type=int, default=1, metavar="N",
        help="trace N configs in parallel (thread pool; report order "
        "stays deterministic - input order, not completion order)",
    )
    args = ap.parse_args(argv)

    _force_cpu_mesh()
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from distributed_neural_network_tpu import analysis

    if args.list:
        for name in analysis.config_names():
            print(name)
        return 0
    if args.write_manifest and args.check:
        ap.error("--write-manifest and --check are mutually exclusive")
    requested = [n for entry in args.config for n in entry.split(",") if n]
    known = analysis.config_names()
    unknown = [n for n in requested if n not in known]
    if unknown:
        print(
            f"unknown shardlint config(s): {', '.join(unknown)}\n"
            f"known configs: {', '.join(known)}"
        )
        return 2
    names = known if args.all or not requested else requested
    mode = (
        "write" if args.write_manifest else "check" if args.check else "lint"
    )
    rc, report = analysis.run_shardlint(
        names, mode=mode, manifest_dir=args.manifest_dir,
        verbose=not args.quiet, explain=args.explain, jobs=args.jobs,
    )
    print(report)
    return rc


if __name__ == "__main__":
    sys.exit(main())
