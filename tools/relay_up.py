#!/usr/bin/env python
"""Exit 0 iff the axon relay's listener ports accept TCP connections.

The relay (the container's only path to the TPU terminal) can die
mid-session (r4 post-mortem in ROADMAP.md): every later RPC then blocks
tens of minutes in retry before erroring, INCLUDING the jax matmul
probes the measurement scripts poll with - a dead-relay poll cycle costs
~50 minutes. This check costs milliseconds and claims nothing: a plain
TCP connect to the relay's device port and its remote-compile port
(immediately closed; the relay just logs an open/EOF pair). Gate the
expensive jax probe on it:

    python tools/relay_up.py && <jax probe>

A listening relay does not guarantee a healthy terminal behind it - the
jax probe stays the real health check; this only prevents probing into
a dead transport.
"""

from __future__ import annotations

import os
import socket
import sys

# one device-traffic port and the remote-compile port (see the PORTS
# list in the relay; these two are the ones measurement traffic needs).
# Overridable via RELAY_PORTS="8082,8113" so a relay with a different
# port layout doesn't pin every gated watcher at "down" forever (the
# callers' rc-2 fall-through handles a *crashed* gate; this handles a
# *wrong* one).
_DEFAULT_PORTS = (8082, 8113)


def _ports() -> tuple[int, ...]:
    raw = os.environ.get("RELAY_PORTS", "").strip()
    if not raw:
        return _DEFAULT_PORTS
    # a separator-only value must not yield an empty tuple: zero ports
    # would make relay_up() vacuously True and report a dead relay "up"
    return (tuple(int(p) for p in raw.replace(" ", "").split(",") if p)
            or _DEFAULT_PORTS)


def relay_up(timeout: float = 2.0) -> bool:
    for port in _ports():
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(timeout)
        try:
            s.connect(("127.0.0.1", port))
        except OSError:
            return False
        finally:
            s.close()
    return True


if __name__ == "__main__":
    # exit codes: 0 up, 1 down, 2 the gate itself broke - callers must
    # treat 2 as "gate unusable, fall through to the real probe", never
    # as "down", or a crashed gate silently pins a watcher at down
    try:
        up = relay_up()
    except Exception as e:  # noqa: BLE001 - any crash must exit 2
        print(f"relay gate error: {type(e).__name__}: {e}")
        sys.exit(2)
    print(f"relay {'up' if up else 'down'}")
    sys.exit(0 if up else 1)
