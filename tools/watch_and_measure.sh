#!/bin/bash
# Probe the axon chip in a loop; on the first healthy probe, run the full
# measurement session. NEVER kills a probe - a wedged claim makes the
# probe itself block 30-50 min before erroring, which IS the polling
# interval (killing a claimer is what wedges the chip; r4 post-mortem).
# Run detached:  setsid nohup bash tools/watch_and_measure.sh \
#                    > watch_measure.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
# single-instance lock shared with fill_missing.sh: two gate-synchronized
# chip watchers would fire claimers at the same gate-open instant (the r4
# wedge condition)
exec 9>".chip_session.lock"
if ! flock -n 9; then
  echo "[watch] another chip watcher holds the lock; waiting for it"
  flock 9
  echo "[watch] lock acquired at $(date -u +%H:%M:%S)"
fi
# refuse to start while another measurement session is live (two claimers
# wedge the chip). Anchored to a python first token: an unanchored name
# match also hits unrelated processes embedding these filenames in argv
while pgrep -f "^[^ ]*python[0-9.]* [^ ]*(bench|tune_flash|measure_all|flash_parity_check)\.py" \
    > /dev/null; do
  echo "[watch] a measurement session is still running; sleeping 120s"
  sleep 120
done
attempt=0
while true; do
  attempt=$((attempt + 1))
  # cheap TCP gate first: with the relay dead (r4 post-mortem), a jax
  # probe blocks ~50 min in RPC retries; this check costs milliseconds
  # and holds no claim, so the poll interval stays 60s. rc 2 = the gate
  # itself crashed - log it and fall through to the real probe rather
  # than silently spinning at "down" forever
  gate_out=$(python tools/relay_up.py 2>&1); gate_rc=$?
  if [ "$gate_rc" -eq 1 ]; then
    echo "[watch] relay down (attempt ${attempt}) at $(date -u +%H:%M:%S); sleeping 60s"
    sleep 60
    continue
  elif [ "$gate_rc" -ne 0 ]; then
    echo "[watch] relay gate unusable (rc ${gate_rc}): ${gate_out} - falling through to the jax probe"
  fi
  echo "[watch] probe attempt ${attempt} at $(date -u +%H:%M:%S)"
  if python -c "
import time, jax, jax.numpy as jnp
t0 = time.time()
x = jnp.ones((512, 512), jnp.bfloat16)
v = float((x @ x).sum())
print('probe ok: value', v, 'in', round(time.time() - t0, 1), 's', flush=True)
"; then
    echo "[watch] chip healthy - starting measure_all at $(date -u +%H:%M:%S)"
    python tools/measure_all.py
    echo "[watch] measure_all done rc=$? at $(date -u +%H:%M:%S)"
    break
  fi
  echo "[watch] probe failed; sleeping 180s before the next attempt"
  sleep 180
done
