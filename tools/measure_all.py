#!/usr/bin/env python
"""One-shot TPU measurement session: every round artifact in one command.

Runs, strictly serially (the single axon chip wedges if two processes
race for the claim, and a kill mid-claim wedges it for everyone):

  1. tools/tune_flash.py          -> tools/flash_tune_<dev>.json
  2. bench.py (full 25-ep matrix) -> BENCH_MATRIX.json (+ headline line)
  3. report.py --from-matrix      -> REPORT.md (no re-measurement)

Each stage gets a generous subprocess timeout but is NOT killed early on
a busy backend - bench.py's own probe gate handles that. Stage failures
are recorded and later stages still run (report renders whatever the
matrix holds, including error rows).

Usage: python tools/measure_all.py [--skip tune] [--bench-args "..."]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(name: str, cmd: list[str], timeout: float) -> dict:
    """Run one stage in its own PROCESS GROUP.

    A timeout kills the whole group (os.killpg), not just the direct
    child: bench.py runs its accelerator rows in a `--worker-multi`
    grandchild holding the single chip claim, and killing only bench.py
    would orphan that grandchild - an invisible claim holder blocking
    every later process (the r4 wedge failure mode). The kill still
    wedges the claim (any mid-claim kill does), but the state is visible
    and bounded instead of a silent orphan.
    """
    import signal

    print(f"[measure_all] {name}: {' '.join(cmd)}", flush=True)
    t0 = time.time()
    proc = subprocess.Popen(
        cmd, cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, start_new_session=True,
    )
    try:
        out, _ = proc.communicate(timeout=timeout)
        ok = proc.returncode == 0
        tail = (out or "")[-1500:]
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        out, _ = proc.communicate()
        ok, tail = False, (
            f"timed out after {timeout:.0f}s (process group killed)\n"
            + (out or "")[-1200:]
        )
    rec = {"stage": name, "ok": ok, "wall_s": round(time.time() - t0, 1),
           "tail": tail}
    print(f"[measure_all] {name}: {'ok' if ok else 'FAILED'} "
          f"({rec['wall_s']}s)\n{tail[-400:]}", flush=True)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--skip", nargs="*", default=[],
                    choices=["tune", "bench", "report"])
    ap.add_argument("--bench-args", default="",
                    help="extra args appended to the bench.py invocation")
    args = ap.parse_args()
    py = sys.executable
    log = []
    # Stage timeouts are LAST-RESORT bounds, not budgets: killing a
    # process that holds (or is acquiring) the chip claim wedges the
    # backend for everyone after (r4 post-mortem - the killed report.py,
    # which used to call jax.devices(), wedged the session). tune and
    # bench hold claims, so their caps are far above any plausible run;
    # report no longer touches the backend at all on --from-matrix.
    if "tune" not in args.skip:
        log.append(run("tune_flash",
                       [py, os.path.join(REPO, "tools", "tune_flash.py")],
                       timeout=5400))
        # second tune at the MXU-native head geometry (H=4 x Dh=128, the
        # bench hd128 row): tuned_blocks() matches tune files by head_dim,
        # so without this the hd128 row runs on default blocks
        log.append(run("tune_flash_hd128",
                       [py, os.path.join(REPO, "tools", "tune_flash.py"),
                        "--heads", "4", "--head-dim", "128"],
                       timeout=5400))
    if "bench" not in args.skip:
        # --refresh: the measurement session re-measures EVERYTHING (old
        # rows may predate the tuned/own kernels); without it bench.py
        # keeps measured rows and runs only headline + missing rows (the
        # driver's short round-end mode)
        log.append(run(
            "bench",
            [py, os.path.join(REPO, "bench.py"), "--deadline", "7200",
             "--refresh", *([a for a in args.bench_args.split() if a])],
            # last-resort only: bench's genuine worst case (every row
            # running to near its 2*est_s+300 cap un-killed) sums past
            # 40000 s, so anything lower risks killpg-ing a HEALTHY
            # claim-holding grandchild (the r4 wedge failure mode).
            # bench's own per-row caps are the real bounds; this fires
            # only on a pathological parent hang.
            timeout=43200,
        ))
    if "report" not in args.skip:
        log.append(run(
            "report",
            [py, os.path.join(REPO, "report.py"), "--from-matrix"],
            timeout=900,
        ))
    out = os.path.join(REPO, "tools", "measure_all_log.json")
    with open(out, "w") as f:
        json.dump(log, f, indent=1)
    print(f"[measure_all] wrote {out}")
    return 0 if all(r["ok"] for r in log) else 1


if __name__ == "__main__":
    sys.exit(main())
