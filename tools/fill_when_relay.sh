#!/bin/bash
# Relay-gated wrapper for the fill pass: poll the relay's TCP listeners
# (milliseconds, no chip claim - tools/relay_up.py) and only hand off to
# tools/fill_missing.sh once the transport exists. Without the gate a
# dead relay costs ~50 minutes per blocked jax probe (ROADMAP r4
# post-mortem). fill_missing.sh itself still does the real jax probe
# and refuses to run beside another measurement session.
# Run detached:  setsid nohup bash tools/fill_when_relay.sh \
#                    > fill_when_relay.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
attempt=0
while true; do
  attempt=$((attempt + 1))
  gate_out=$(python tools/relay_up.py 2>&1); gate_rc=$?
  if [ "$gate_rc" -eq 0 ]; then
    echo "[gate] relay up at $(date -u +%H:%M:%S) - starting fill"
    exec bash tools/fill_missing.sh
  elif [ "$gate_rc" -ne 1 ]; then
    echo "[gate] relay gate unusable (rc ${gate_rc}): ${gate_out} - starting fill anyway"
    exec bash tools/fill_missing.sh
  fi
  if [ $((attempt % 30)) -eq 1 ]; then
    echo "[gate] relay down (attempt ${attempt}) at $(date -u +%H:%M:%S)"
  fi
  sleep 60
done
