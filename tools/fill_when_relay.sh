#!/bin/bash
# Relay-gated wrapper for the fill pass: poll the relay's TCP listeners
# (milliseconds, no chip claim - tools/relay_up.py) and only hand off to
# tools/fill_missing.sh once the transport exists. Without the gate a
# dead relay costs ~50 minutes per blocked jax probe (ROADMAP r4
# post-mortem). fill_missing.sh itself still does the real jax probe
# and refuses to run beside another measurement session.
# Run detached:  setsid nohup bash tools/fill_when_relay.sh \
#                    > fill_when_relay.log 2>&1 &
#
# Lifetime note: this wrapper exists because fill_missing.sh cannot be
# edited while a live bash process is still executing it (bash reads
# scripts incrementally). Once no fill_missing.sh process survives,
# inline the relay gate into fill_missing.sh's own probe loop (the
# watch_and_measure.sh block is the template) and retire this file -
# a relay death AFTER the handoff still costs ~50 min per blocked jax
# probe, which only an in-loop gate fixes.
set -u
cd "$(dirname "$0")/.."

handoff() {
  # one watcher at a time: watch_and_measure's inline jax probe does not
  # match fill_missing's python-script guard, so two gate-synchronized
  # watchers would fire claimers at the same gate-open instant - the
  # r4 wedge condition. Script-level pgrep sees both watchers reliably.
  while pgrep -f "watch_and_measure\.sh|measure_all\.py" > /dev/null; do
    echo "[gate] another chip watcher is running; sleeping 120s"
    sleep 120
  done
  exec bash tools/fill_missing.sh
}

attempt=0
while true; do
  attempt=$((attempt + 1))
  gate_out=$(python tools/relay_up.py 2>&1); gate_rc=$?
  if [ "$gate_rc" -eq 0 ]; then
    echo "[gate] relay up at $(date -u +%H:%M:%S) - starting fill"
    handoff
  elif [ "$gate_rc" -ne 1 ]; then
    echo "[gate] relay gate unusable (rc ${gate_rc}): ${gate_out} - starting fill anyway"
    handoff
  fi
  if [ $((attempt % 30)) -eq 1 ]; then
    echo "[gate] relay down (attempt ${attempt}) at $(date -u +%H:%M:%S)"
  fi
  sleep 60
done
