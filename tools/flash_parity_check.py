#!/usr/bin/env python
"""On-TPU numerics parity for the framework's own Pallas kernels.

The CPU suite exercises `ops/flash_pallas.py` and `ops/pallas_kernels.py`
through the Pallas *interpreter* (tests/test_flash_pallas.py,
tests/test_pallas.py); Mosaic-compiled behavior is only truly covered on
TPU, and the r4 hardware session measured *timing*, not parity (r4
VERDICT weak #4). This script runs on the real chip, under the same
single claim as the fill pass, and checks:

  1. own flash fwd+bwd, compiled Mosaic vs the Pallas interpreter on the
     SAME f32 inputs (small shape) - the exact "compiled != interpreter"
     question;
  2. own flash fwd+bwd (bf16, production seq 2048, the tuned blocks
     `tuned_blocks()` resolves) vs XLA fused attention - end-to-end
     numerics at the geometry the flagship LM row trains with;
  3. the fused Pallas CNN head (compiled) vs `mlp3_reference` fwd+bwd.

Writes tools/flash_parity_<device>.json: one row per check with a
normalized max-abs error (max|a-b| / (max|b|+eps)) and pass/fail, plus
an overall "ok". Exit 0 iff every row passed.

Usage (real TPU, one claim):  python tools/flash_parity_check.py
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _err(a, b, eps=1e-12):
    """Normalized max-abs error: comparable across output/grad scales."""
    import numpy as np

    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    return float(np.max(np.abs(a - b)) / (np.max(np.abs(b)) + eps))


def main() -> int:
    import jax
    import jax.numpy as jnp

    from distributed_neural_network_tpu.ops.flash import tuned_blocks
    from distributed_neural_network_tpu.ops.flash_pallas import flash_mha
    from distributed_neural_network_tpu.ops.pallas_kernels import (
        fused_mlp3,
        mlp3_reference,
    )

    if jax.default_backend() != "tpu":
        print(json.dumps({"error": "parity check needs a TPU backend"}))
        return 1

    rows = []

    def check(name, err, tol, extra=None):
        row = {"check": name, "err": round(err, 6), "tol": tol,
               "pass": bool(err <= tol)}
        if extra:
            row.update(extra)
        print(json.dumps(row), flush=True)
        rows.append(row)

    def fb(attn):
        """Forward output + input grads of a scalar loss, one jit."""
        def f(q, k, v):
            def loss(q, k, v):
                return (attn(q, k, v).astype(jnp.float32) ** 2).mean()

            out = attn(q, k, v)
            gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            return out, gq, gk, gv

        return jax.jit(f)

    # --- 1. compiled Mosaic vs Pallas interpreter, f32, small shape ----
    # Multi-block grid on every axis (S=512, ALL blocks 256 - forward
    # and both backward kernels) so the check exercises the block loops
    # and their accumulation carries, not a single-tile special case.
    B, H, S, D = 2, 2, 512, 64
    ks = jax.random.split(jax.random.key(7), 3)
    q32, k32, v32 = (jax.random.normal(k, (B, S, H, D), jnp.float32)
                     for k in ks)
    try:
        from distributed_neural_network_tpu.ops.flash_pallas import (
            FlashBlocks,
        )

        blocks = FlashBlocks(bq=256, bk=256, bq_dq=256, bk_dq=256,
                             bq_dkv=256, bk_dkv=256)
        comp = fb(lambda q, k, v: flash_mha(
            q, k, v, causal=True, blocks=blocks))(q32, k32, v32)
        interp = fb(lambda q, k, v: flash_mha(
            q, k, v, causal=True, blocks=blocks, interpret=True))(
            q32, k32, v32)
        for part, a, b in zip(("out", "dq", "dk", "dv"), comp, interp):
            check(f"flash_compiled_vs_interpreter_f32_{part}",
                  _err(a, b), 2e-4)
    except Exception as e:  # noqa: BLE001 - record, keep checking
        rows.append({"check": "flash_compiled_vs_interpreter_f32",
                     "error": str(e)[:300], "pass": False})
        print(json.dumps(rows[-1]), flush=True)

    # --- 2. own kernel (bf16, production geometry + tuned blocks) vs
    # XLA fused attention (f32 scores) ---------------------------------
    B, H, S, D = 4, 8, 2048, 64
    ks = jax.random.split(jax.random.key(11), 3)
    qb, kb, vb = (jax.random.normal(k, (B, S, H, D), jnp.bfloat16)
                  for k in ks)

    def xla_attn(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(D)
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s.astype(jnp.float32), -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    try:
        tb = tuned_blocks(S, D)
        own = fb(lambda q, k, v: flash_mha(
            q, k, v, causal=True, blocks=tb))(qb, kb, vb)
        ref = fb(xla_attn)(qb, kb, vb)
        # bf16 storage + blockwise-softmax reassociation: loose tol
        for part, a, b in zip(("out", "dq", "dk", "dv"), own, ref):
            check(f"flash_own_vs_xla_bf16_s{S}_{part}", _err(a, b), 3e-2,
                  {"blocks": {f: getattr(tb, f) for f in (
                      "bq", "bk", "bq_dq", "bk_dq", "bq_dkv", "bk_dkv")}}
                  if part == "out" else None)
    except Exception as e:  # noqa: BLE001
        rows.append({"check": "flash_own_vs_xla_bf16", "error": str(e)[:300],
                     "pass": False})
        print(json.dumps(rows[-1]), flush=True)

    # --- 3. fused CNN head (compiled Mosaic) vs plain-jnp reference ----
    din, dh1, dh2, dout, nb = 400, 120, 84, 10, 64
    ks = jax.random.split(jax.random.key(13), 7)
    x = jax.random.normal(ks[0], (nb, din), jnp.float32)
    w1 = jax.random.normal(ks[1], (din, dh1), jnp.float32) * 0.05
    b1 = jax.random.normal(ks[2], (dh1,), jnp.float32) * 0.05
    w2 = jax.random.normal(ks[3], (dh1, dh2), jnp.float32) * 0.05
    b2 = jax.random.normal(ks[4], (dh2,), jnp.float32) * 0.05
    w3 = jax.random.normal(ks[5], (dh2, dout), jnp.float32) * 0.05
    b3 = jax.random.normal(ks[6], (dout,), jnp.float32) * 0.05
    params = (w1, b1, w2, b2, w3, b3)

    def head_fb(head):
        def f(x, *ps):
            def loss(x, *ps):
                return (head(x, *ps) ** 2).mean()

            out = head(x, *ps)
            grads = jax.grad(loss, argnums=tuple(range(7)))(x, *ps)
            return (out,) + grads

        return jax.jit(f)

    try:
        with jax.default_matmul_precision("highest"):
            comp = head_fb(lambda *a: fused_mlp3(*a, interpret=False))(
                x, *params)
            ref = head_fb(mlp3_reference)(x, *params)
        names = ("out", "dx", "dw1", "db1", "dw2", "db2", "dw3", "db3")
        for part, a, b in zip(names, comp, ref):
            check(f"mlp3_compiled_vs_reference_f32_{part}", _err(a, b), 5e-3)
    except Exception as e:  # noqa: BLE001
        rows.append({"check": "mlp3_compiled_vs_reference", "pass": False,
                     "error": str(e)[:300]})
        print(json.dumps(rows[-1]), flush=True)

    ok = bool(rows) and all(r.get("pass") for r in rows)
    dev = jax.devices()[0].device_kind.replace(" ", "_")
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            f"flash_parity_{dev}.json")
    with open(out_path, "w") as f:
        json.dump({"device": dev, "ok": ok, "rows": rows}, f, indent=1)
    print(json.dumps({"wrote": out_path, "ok": ok,
                      "checks": len(rows)}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
