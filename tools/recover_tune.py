#!/usr/bin/env python
"""Reconstruct a `flash_tune_*.json` file from a measurement-session log.

Why this exists: `tools/tune_flash.py` streams one JSON row per timed
config but writes its output file only at the END of the sweep. When the
axon tunnel dies mid-sweep (r4 second pass: the relay process carrying
the tunnel died and every later RPC burns a ~50-minute retry window
before erroring), the measured rows - including the best backward-block
combination the whole sweep exists to find - survive only in the log.
This tool re-derives the tuner's payload from those rows so
`ops/flash.py tuned_blocks()` and REPORT.md's MFU-ceiling section can
consume the measurements without re-running the sweep on a dead chip.

Scope: the reconstruction covers exactly what the log rows contain. The
per-pass ablation is derived with the tuner's own pairing rule (fwd and
fwd+bwd of the SAME variant); sections whose rows never ran (e.g. the
library baselines when the tunnel died first) are emitted as None, the
same shape a completed-but-errored sweep produces. The payload carries
`"recovered_from_log"` so provenance stays visible, and the tool refuses
to overwrite a file the real tuner wrote (no marker) unless --force.

Shape/device are NOT in the log rows; they come from flags whose
defaults match `tune_flash.py`'s defaults (the hd64 flagship geometry).

COUPLING: `rebuild()` mirrors the payload logic at the end of
`tune_flash.py main()` (pairing rule, FLOP conventions, payload keys) -
if that changes, change this in lockstep. They stay two copies rather
than one shared module because tune_flash.py is executed by live
measurement sessions that must never be edited mid-run; the mirror is
pinned by tests/test_recover_tune.py.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

_FB = re.compile(r"^own_fb_q(\d+(?:x\d+)?)_dq(\d+(?:x\d+)?)_dkv(\d+(?:x\d+)?)$")


def _pair(tag: str) -> tuple[int, int]:
    """"512" -> (512, 512); "512x1024" -> (512, 1024)."""
    if "x" in tag:
        a, b = tag.split("x", 1)
        return int(a), int(b)
    return int(tag), int(tag)


def parse_segments(lines: list[str]) -> list[list[dict]]:
    """Split a session log into tuner-run segments of {"cfg": ...} rows.

    A segment ends at the tuner's final `{"wrote": ...}` line, or when a
    cfg name repeats (a fresh tuner run restarting its sweep without a
    "wrote" line - the tunnel-death case this tool exists for)."""
    segments: list[list[dict]] = []
    cur: list[dict] = []
    seen: set[str] = set()
    for ln in lines:
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            row = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if not isinstance(row, dict):
            continue
        if "wrote" in row:
            if cur:
                segments.append(cur)
            cur, seen = [], set()
            continue
        cfg = row.get("cfg")
        if not isinstance(cfg, str):
            continue
        if cfg in seen:
            segments.append(cur)
            cur, seen = [], set()
        cur.append(row)
        seen.add(cfg)
    if cur:
        segments.append(cur)
    return segments


def rebuild(rows: list[dict], *, batch: int, heads: int, seq: int,
            head_dim: int, device: str) -> dict:
    """The tuner's payload (tune_flash.py's `payload` dict) from its
    streamed rows, with `recovered_from_log` provenance."""
    fb_ok = [(r, _FB.match(r["cfg"])) for r in rows
             if "ms" in r and _FB.match(r.get("cfg", ""))]
    best_own, best_own_ms = None, None
    best_tag = None
    for r, m in fb_ok:
        if best_own_ms is None or r["ms"] < best_own_ms:
            (bq, bk) = _pair(m.group(1))
            (bq_dq, bk_dq) = _pair(m.group(2))
            (bq_dkv, bk_dkv) = _pair(m.group(3))
            best_own = {"bq": bq, "bk": bk, "bq_dq": bq_dq, "bk_dq": bk_dq,
                        "bq_dkv": bq_dkv, "bk_dkv": bk_dkv}
            best_own_ms, best_tag = r["ms"], m.group(1)

    # fwd ms of the SAME forward blocks every fb config used (the tuner's
    # pairing rule: bwd is only derivable when fwd configs match)
    f_own = None
    if best_tag is not None:
        bq, bk = _pair(best_tag)
        f_own = next((r["ms"] for r in rows
                      if r.get("cfg") == f"own_fwd_q{bq}k{bk}"
                      and "ms" in r), None)

    fwd_flops = 2.0 * batch * heads * seq * seq * head_dim

    def tflops(flops, ms):
        return None if not ms else round(flops / (ms / 1e3) / 1e12, 2)

    def paired(fwd_p: str, fb_p: str):
        """(fwd_ms, fb_ms, matched) - the tuner's paired_ms rule,
        including its fallback: when no variant has BOTH timings, keep
        the best of whatever was measured (a lone lib_fwd row from a
        sweep the tunnel cut short must not vanish), but flag the pair
        unmatched so bwd is never derived across mismatched configs."""
        fwd_by = {r["cfg"][len(fwd_p):]: r["ms"] for r in rows
                  if r.get("cfg", "").startswith(fwd_p) and "ms" in r}
        fb_by = {r["cfg"][len(fb_p):]: r["ms"] for r in rows
                 if r.get("cfg", "").startswith(fb_p) and "ms" in r}
        both = [v for v in fb_by if v in fwd_by]
        if not both:
            return (min(fwd_by.values()) if fwd_by else None,
                    min(fb_by.values()) if fb_by else None, False)
        v = min(both, key=fb_by.get)
        return fwd_by[v], fb_by[v], True

    ablation = {}
    for name, fwd_p, fb_p in (("lib", "lib_fwd_", "lib_fb_"),
                              ("xla", "xla_fwd", "xla_fb")):
        f, fb, matched = paired(fwd_p, fb_p)
        bwd = (None if f is None or fb is None or not matched
               else round(fb - f, 2))
        ablation[name] = {
            "fwd_ms": f, "fwdbwd_ms": fb, "bwd_ms_derived": bwd,
            "fwd_attn_tflops_per_s": tflops(fwd_flops, f),
            "bwd_attn_tflops_per_s": tflops(2.5 * fwd_flops, bwd),
        }
    bwd_own = (None if f_own is None or best_own_ms is None
               else round(best_own_ms - f_own, 2))
    ablation["own"] = {
        "fwd_ms": f_own, "fwdbwd_ms": best_own_ms,
        "bwd_ms_derived": bwd_own,
        "fwd_attn_tflops_per_s": tflops(fwd_flops, f_own),
        "bwd_attn_tflops_per_s": tflops(2.5 * fwd_flops, bwd_own),
    }

    lib_fb = [r for r in rows
              if r.get("cfg", "").startswith("lib_fb_") and "ms" in r]
    return {
        "shape": {"batch": batch, "heads": heads, "seq": seq,
                  "head_dim": head_dim},
        "device": device,
        "rows": rows,
        "best_own": best_own,
        "best_own_ms": best_own_ms,
        "best_lib_fwdbwd": (min(lib_fb, key=lambda r: r["ms"])
                            if lib_fb else None),
        "ablation": ablation,
        "recovered_from_log": True,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--log", required=True, help="session log to parse")
    ap.add_argument("--segment", type=int, default=0,
                    help="which tuner run in the log (0 = first)")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--device", default="TPU_v5_lite",
                    help="device kind as jax reports it, spaces as _")
    ap.add_argument("--out", default=None,
                    help="output path (default: the tuner's own filename "
                         "convention next to this script)")
    ap.add_argument("--force", action="store_true",
                    help="overwrite even a file the real tuner wrote")
    args = ap.parse_args()

    with open(args.log) as f:
        segments = parse_segments(f.readlines())
    if not segments or args.segment >= len(segments):
        print(json.dumps({"error": f"no tuner segment {args.segment} in "
                                   f"{args.log} ({len(segments)} found)"}))
        return 1
    payload = rebuild(segments[args.segment], batch=args.batch,
                      heads=args.heads, seq=args.seq,
                      head_dim=args.head_dim, device=args.device)
    if payload["best_own"] is None:
        print(json.dumps({"error": "segment has no measured own_fb row"}))
        return 1

    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"flash_tune_{args.device}_s{args.seq}_d{args.head_dim}.json"
        if args.head_dim != 64
        else f"flash_tune_{args.device}_s{args.seq}.json",
    )
    if os.path.exists(out) and not args.force:
        try:
            with open(out) as f:
                existing = json.load(f)
        except (OSError, json.JSONDecodeError):
            existing = None  # unreadable/corrupt - NOT real tuner data
        if existing is None:
            print(json.dumps({"error": f"{out} exists but is unreadable/"
                                       "corrupt; use --force to replace"}))
            return 1
        if not existing.get("recovered_from_log"):
            print(json.dumps({"error": f"{out} was written by the real "
                                       "tuner; use --force to replace"}))
            return 1
    with open(out, "w") as f:
        json.dump(payload, f, indent=1)
    print(json.dumps({"wrote": out, "best_own": payload["best_own"],
                      "best_own_ms": payload["best_own_ms"],
                      "n_rows": len(payload["rows"]),
                      "recovered_from_log": True}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
