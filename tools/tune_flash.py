#!/usr/bin/env python
"""Tune flash-attention block sizes with honest fencing (hard_block).

Round-2 note: the original tuning (uniform 1024 blocks, "2.3x faster than
XLA") was measured with `jax.block_until_ready` as the fence - which is a
no-op on the axon tunnel backend, so those numbers were dispatch time and
are retracted. Everything here fences with `hard_block` (value fetch).

Round 4: the framework's OWN kernels (ops/flash_pallas.py) are the
default flash path, with independently tunable forward and backward
blocks - the r3 MFU diagnosis put the gap in the backward pass (fwd ~45%
MXU efficiency, bwd ~25%), so the sweep is staged: forward blocks first
(fwd-only timing), then a (dq x dkv) grid at the best forward blocks
(fwd+bwd timing). The library kernel and XLA fused attention run as
baselines. Writes tools/flash_tune_<device>_s<seq>.json with `best_own`
in exactly the FlashBlocks-field format `ops/flash.py tuned_blocks()`
loads at run time.

Usage (on real TPU):  python tools/tune_flash.py [--seq 2048] [--batch 16]
"""

from __future__ import annotations

import argparse
import functools
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--skip-lib", action="store_true",
                    help="skip the library-kernel baseline rows")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from distributed_neural_network_tpu.ops.flash_pallas import (
        FlashBlocks,
        flash_mha,
    )
    from distributed_neural_network_tpu.utils.timers import (
        fence_rtt,
        hard_block,
    )

    if jax.default_backend() != "tpu":
        print(json.dumps({"error": "flash tuning needs a TPU backend"}))
        return 1

    B, H, S, D = args.batch, args.heads, args.seq, args.head_dim
    # (B, S, H, D) - the framework's layout (own kernel transposes inside)
    q = jax.random.normal(jax.random.key(0), (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, S, H, D), jnp.bfloat16)

    def xla_attn(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(D)
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s.astype(jnp.float32), -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    def fwdbwd(attn):
        def f(q, k, v):
            def loss(q, k, v):
                return (attn(q, k, v).astype(jnp.float32) ** 2).mean()

            l, gs = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
            return l, gs[0].sum(), gs[1].sum(), gs[2].sum()

        return f

    results = []

    def timeit(name, f):
        g = jax.jit(f)
        try:
            out = g(q, k, v)
            hard_block(out)
            # subtract the pure fence round-trip (~60-70 ms through the
            # tunnel), which would otherwise inflate every row by
            # rtt/steps (~3 ms at 20 steps) and bias the fwd-vs-bwd
            # ablation splits (utils/timers.py fence_rtt)
            rtt = fence_rtt(out)
            t0 = time.perf_counter()
            for _ in range(args.steps):
                out = g(q, k, v)
            hard_block(out)
            ms = max(time.perf_counter() - t0 - rtt, 1e-9) / args.steps * 1e3
            row = {"cfg": name, "ms": round(ms, 2)}
        except Exception as e:  # noqa: BLE001 - report and continue tuning
            row = {"cfg": name, "error": str(e)[:200]}
        print(json.dumps(row), flush=True)
        results.append(row)
        return row

    def own(blocks):
        return functools.partial(flash_mha, causal=True, blocks=blocks)

    cand = [b for b in (256, 512, 1024) if S % b == 0] or [S]

    # stage 1: forward blocks - the full ASYMMETRIC (bq, bk) grid, not
    # just uniform pairs: the q block sets the scratch/accumulator
    # footprint while the k block sets the inner-step granularity (and
    # the causal-skip resolution), so the best pair need not be square
    # (the r4 hardware sweep found the library kernel fastest at 512
    # uniform while the own kernel preferred 1024 - sweep both axes)
    fwd_rows = {}
    for bq in cand:
        for bk in cand:
            blocks = FlashBlocks(bq=bq, bk=bk)
            fwd_rows[(bq, bk)] = timeit(f"own_fwd_q{bq}k{bk}", own(blocks))
    ok_fwd = {p: r["ms"] for p, r in fwd_rows.items() if "ms" in r}
    best_fwd_pair = (min(ok_fwd, key=ok_fwd.get) if ok_fwd
                     else (cand[0], cand[0]))
    fwd_tag = (f"{best_fwd_pair[0]}" if best_fwd_pair[0] == best_fwd_pair[1]
               else f"{best_fwd_pair[0]}x{best_fwd_pair[1]}")

    # stage 2: backward blocks at the best forward blocks (fwd+bwd
    # timing), staged to keep the grid small: symmetric dq sweep at a
    # fixed dkv, then an ASYMMETRIC (bq_dkv, bk_dkv) sweep (the 3-D-grid
    # dkv kernel's inner q block and outer k block are independent
    # levers), then an asymmetric dq refinement at the best dkv.
    best_own, best_own_ms = None, float("inf")
    _seen = {}

    def try_fb(name, **fields):
        nonlocal best_own, best_own_ms
        blocks = FlashBlocks(bq=best_fwd_pair[0], bk=best_fwd_pair[1],
                             **fields)
        if blocks in _seen:  # identical config under another stage's name
            return _seen[blocks]
        r = timeit(name, fwdbwd(own(blocks)))
        _seen[blocks] = r
        if "ms" in r and r["ms"] < best_own_ms:
            best_own_ms, best_own = r["ms"], blocks
        return r

    mid = cand[len(cand) // 2]
    sweep = {}
    for bdq in cand:
        r = try_fb(f"own_fb_q{fwd_tag}_dq{bdq}_dkv{mid}",
                   bq_dq=bdq, bk_dq=bdq, bq_dkv=mid, bk_dkv=mid)
        if "ms" in r:
            sweep[(bdq, bdq)] = r["ms"]
    best_dq = min(sweep, key=sweep.get) if sweep else (mid, mid)
    sweep = {}
    for bq_dkv in cand:
        for bk_dkv in cand:
            r = try_fb(
                f"own_fb_q{fwd_tag}_dq{best_dq[0]}_"
                f"dkv{bq_dkv}x{bk_dkv}",
                bq_dq=best_dq[0], bk_dq=best_dq[1],
                bq_dkv=bq_dkv, bk_dkv=bk_dkv,
            )
            if "ms" in r:
                sweep[(bq_dkv, bk_dkv)] = r["ms"]
    best_dkv = min(sweep, key=sweep.get) if sweep else (mid, mid)
    for bq_dq in cand:
        for bk_dq in cand:
            # symmetric pairs at THIS dkv were only pre-measured when
            # best_dkv happens to be (mid, mid) - _seen dedupes that case
            try_fb(
                f"own_fb_q{fwd_tag}_dq{bq_dq}x{bk_dq}_"
                f"dkv{best_dkv[0]}x{best_dkv[1]}",
                bq_dq=bq_dq, bk_dq=bk_dq,
                bq_dkv=best_dkv[0], bk_dkv=best_dkv[1],
            )

    # baselines: library kernel (its best uniform blocks) + XLA fused
    if not args.skip_lib:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes,
            flash_attention,
        )

        def uniform(b):
            b = min(b, S)
            return BlockSizes(
                block_q=b, block_k_major=b, block_k=b, block_b=1,
                block_q_major_dkv=b, block_k_major_dkv=b,
                block_q_dkv=b, block_k_dkv=b,
                block_q_dq=b, block_k_dq=b, block_k_major_dq=b,
            )

        def lib(bs):
            def f(q, k, v):
                out = flash_attention(
                    q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                    v.transpose(0, 2, 1, 3), causal=True,
                    sm_scale=1.0 / math.sqrt(D), block_sizes=bs,
                )
                return out.transpose(0, 2, 1, 3)

            return f

        variants = {"defaults": None}
        for b in cand:
            variants[f"uniform{b}"] = uniform(b)
        for name, bs in variants.items():
            timeit(f"lib_fwd_{name}", lib(bs))
            timeit(f"lib_fb_{name}", fwdbwd(lib(bs)))
    timeit("xla_fwd", xla_attn)
    timeit("xla_fb", fwdbwd(xla_attn))

    dev = jax.devices()[0].device_kind.replace(" ", "_")
    # head_dim is part of the filename (D != 64 tunes must not clobber
    # the D=64 file; `tuned_blocks()` globs flash_tune_*.json and matches
    # on the recorded shape, so both spellings load fine)
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"flash_tune_{dev}_s{S}_d{D}.json" if D != 64
        else f"flash_tune_{dev}_s{S}.json",
    )
    lib_fb = [r for r in results
              if r["cfg"].startswith("lib_fb_") and "ms" in r]

    def best_ms(prefix):
        ok = [r["ms"] for r in results
              if r["cfg"].startswith(prefix) and "ms" in r]
        return min(ok) if ok else None

    # per-pass ablation (r3 VERDICT item 2: fwd ~45% / bwd ~25% MXU
    # efficiency with the library kernel - prove where the ceiling is).
    # bwd is derived as fb - fwd (same fwd blocks in both timings).
    # Causal attention FLOPs: fwd = 2 matmuls * 2 flops * B*H*S^2*D / 2
    # (causal half) = 2*B*H*S^2*D; bwd re-forms p and runs 5 matmuls =
    # 2.5x fwd.
    fwd_flops = 2.0 * B * H * S * S * D

    def tflops(flops, ms):
        return None if not ms else round(flops / (ms / 1e3) / 1e12, 2)

    def paired_ms(fwd_p, fb_p):
        """(fwd_ms, fb_ms) from the SAME variant (suffix after the
        prefix), chosen by min fb - deriving bwd as fb - fwd is only
        meaningful when both timings share the forward config."""
        fwd_by = {r["cfg"][len(fwd_p):]: r["ms"] for r in results
                  if r["cfg"].startswith(fwd_p) and "ms" in r}
        fb_by = {r["cfg"][len(fb_p):]: r["ms"] for r in results
                 if r["cfg"].startswith(fb_p) and "ms" in r}
        both = [v for v in fb_by if v in fwd_by]
        if not both:
            return best_ms(fwd_p), best_ms(fb_p), False
        v = min(both, key=fb_by.get)
        return fwd_by[v], fb_by[v], True

    ablation = {}
    for name, fwd_p, fb_p in (("lib", "lib_fwd_", "lib_fb_"),
                              ("xla", "xla_fwd", "xla_fb")):
        f, fb, matched = paired_ms(fwd_p, fb_p)
        bwd = None if f is None or fb is None or not matched else round(
            fb - f, 2)
        ablation[name] = {
            "fwd_ms": f, "fwdbwd_ms": fb, "bwd_ms_derived": bwd,
            "fwd_attn_tflops_per_s": tflops(fwd_flops, f),
            "bwd_attn_tflops_per_s": tflops(2.5 * fwd_flops, bwd),
        }
    # own: every fb config used best_fwd_pair for the forward, so the
    # matching fwd row is exactly own_fwd_q{bq}k{bk} at that pair
    f_own = next((r["ms"] for r in results
                  if r["cfg"] == f"own_fwd_q{best_fwd_pair[0]}k{best_fwd_pair[1]}"
                  and "ms" in r), None)
    fb_own = None if best_own is None else best_own_ms
    bwd_own = None if f_own is None or fb_own is None else round(
        fb_own - f_own, 2)
    ablation["own"] = {
        "fwd_ms": f_own, "fwdbwd_ms": fb_own, "bwd_ms_derived": bwd_own,
        "fwd_attn_tflops_per_s": tflops(fwd_flops, f_own),
        "bwd_attn_tflops_per_s": tflops(2.5 * fwd_flops, bwd_own),
    }

    payload = {
        "shape": {"batch": B, "heads": H, "seq": S, "head_dim": D},
        "device": dev,
        "rows": results,
        "best_own": (
            {f: getattr(best_own, f) for f in
             ("bq", "bk", "bq_dq", "bk_dq", "bq_dkv", "bk_dkv")}
            if best_own else None
        ),
        "best_own_ms": None if best_own is None else best_own_ms,
        "best_lib_fwdbwd": (
            min(lib_fb, key=lambda r: r["ms"]) if lib_fb else None
        ),
        "ablation": ablation,
    }
    with open(out_path, "w") as f:
        json.dump(payload, f, indent=1)
    print(json.dumps({"wrote": out_path, "best_own": payload["best_own"],
                      "best_own_ms": payload["best_own_ms"],
                      "best_lib_fwdbwd": payload["best_lib_fwdbwd"]}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
