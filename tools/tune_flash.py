#!/usr/bin/env python
"""Re-tune the Pallas flash-attention block sizes with honest fencing.

Round-2 note: the original tuning (uniform 1024 blocks, "2.3x faster than
XLA") was measured with `jax.block_until_ready` as the fence - which is a
no-op on the axon tunnel backend, so those numbers were dispatch time.
This tool measures with `hard_block` (value-fetch fence) and reports
fwd-only and fwd+bwd times per block-size variant, plus the XLA fused
attention as the baseline, then prints the winner in the `_block_sizes`
format (ops/flash.py).

Usage (on real TPU):  python tools/tune_flash.py [--seq 2048] [--batch 16]
Writes tools/flash_tune_<device>.json and prints one JSON line per variant.
"""

from __future__ import annotations

import argparse
import functools
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from distributed_neural_network_tpu.utils.timers import hard_block

    if jax.default_backend() != "tpu":
        print(json.dumps({"error": "flash tuning needs a TPU backend"}))
        return 1

    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        flash_attention,
    )

    B, H, S, D = args.batch, args.heads, args.seq, args.head_dim
    q = jax.random.normal(jax.random.key(0), (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, H, S, D), jnp.bfloat16)

    def uniform(b):
        b = min(b, S)
        return BlockSizes(
            block_q=b, block_k_major=b, block_k=b, block_b=1,
            block_q_major_dkv=b, block_k_major_dkv=b,
            block_q_dkv=b, block_k_dkv=b,
            block_q_dq=b, block_k_dq=b, block_k_major_dq=b,
        )

    def xla_attn(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(D)
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s.astype(jnp.float32), -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    def fwdbwd(attn):
        def f(q, k, v):
            def loss(q, k, v):
                return (attn(q, k, v).astype(jnp.float32) ** 2).mean()

            l, gs = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
            return l, gs[0].sum(), gs[1].sum(), gs[2].sum()

        return f

    results = []

    def timeit(name, f):
        g = jax.jit(f)
        try:
            out = g(q, k, v)
            hard_block(out)
            t0 = time.perf_counter()
            for _ in range(args.steps):
                out = g(q, k, v)
            hard_block(out)
            ms = (time.perf_counter() - t0) / args.steps * 1000
            row = {"cfg": name, "ms": round(ms, 2)}
        except Exception as e:  # noqa: BLE001 - report and continue tuning
            row = {"cfg": name, "error": str(e)[:200]}
        print(json.dumps(row), flush=True)
        results.append(row)
        return row

    variants = {"lib-defaults": None}
    for b in (256, 512, 1024):
        if S % b == 0 or b >= S:
            variants[f"uniform{b}"] = uniform(b)

    for name, bs in variants.items():
        fa = functools.partial(
            _flash, flash_attention, bs, 1.0 / math.sqrt(D)
        )
        timeit(f"flash_fwd_{name}", fa)
        timeit(f"flash_fb_{name}", fwdbwd(fa))
    timeit("xla_fwd", xla_attn)
    timeit("xla_fb", fwdbwd(xla_attn))

    dev = jax.devices()[0].device_kind.replace(" ", "_")
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"flash_tune_{dev}_s{S}.json",
    )
    fb = [r for r in results if r["cfg"].startswith("flash_fb_") and "ms" in r]
    best = min(fb, key=lambda r: r["ms"]) if fb else None
    with open(out_path, "w") as f:
        json.dump(
            {"shape": {"batch": B, "heads": H, "seq": S, "head_dim": D},
             "device": dev, "rows": results, "best_fwdbwd": best},
            f, indent=1,
        )
    print(json.dumps({"wrote": out_path, "best_fwdbwd": best}), flush=True)
    return 0


def _flash(flash_attention, bs, scale, q, k, v):
    return flash_attention(
        q, k, v, causal=True, sm_scale=scale, block_sizes=bs
    )


if __name__ == "__main__":
    sys.exit(main())
