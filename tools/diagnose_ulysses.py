#!/usr/bin/env python
"""Component ablation for the Ulysses sp=8 anomaly (r4 VERDICT weak #3).

`lm_ulysses_sp_scaling_cpu8` measured overhead_vs_sp1 0.897 at sp=4 but
1.923 at sp=8 (H=8 heads -> ONE head per device at sp=8). This script
splits one ulysses attention call (parallel/ring.py ulysses_attention)
into its two components and times each per sp on the same virtual CPU
mesh the scaling row used:

  - full:  all_to_all resharding + local full attention + all_to_all back
  - a2a:   the four tiled all_to_alls alone (trivial compute between)
  - attn:  the local attention alone on head-sharded inputs
           (B, S_full, H/sp, D) - no collectives

plus a mesh-free single-device attention timing at each H/sp value, to
separate "the (B, S, 1, D) einsum itself is slow" from "the collective
or its layout transforms blow up at 8 participants".

Timing is fwd+bwd (jax.value_and_grad of a scalar loss), matching the
train-step measurement that exposed the anomaly. Writes
tools/ulysses_diag.json.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/diagnose_ulysses.py
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()
# hard-set, not setdefault: the baked environment ships JAX_PLATFORMS=axon,
# and a CPU-mesh diagnostic must never touch the chip claim
os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> int:
    from distributed_neural_network_tpu.train.cli import honor_platform_env

    honor_platform_env()
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from distributed_neural_network_tpu.parallel.ring import (
        attention,
        ulysses_attention,
    )
    from distributed_neural_network_tpu.utils.timers import hard_block

    B, S, H, D = 2, 2048, 8, 16  # the scaling row's geometry (d_model 128)
    steps = 3
    dev = jax.devices()
    rows = []

    def timeit(name, f, *args):
        out = f(*args)
        hard_block(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = f(*args)
        hard_block(out)
        ms = (time.perf_counter() - t0) / steps * 1e3
        row = {"cfg": name, "ms": round(ms, 1)}
        print(json.dumps(row), flush=True)
        rows.append(row)
        return ms

    def fb(fn, axis=None):
        def f(q, k, v):
            def loss(q, k, v):
                return (fn(q, k, v) ** 2).mean()

            l, gs = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
            if axis is not None:  # replicate the scalar for out_specs P()
                l = jax.lax.pmean(l, axis)
            return l, gs[0], gs[1], gs[2]

        return f

    for sp in (2, 4, 8):
        mesh = Mesh(dev[:sp], ("seq",))
        seq_sh = NamedSharding(mesh, P(None, "seq"))
        ks = jax.random.split(jax.random.key(3), 3)
        qkv = [jax.device_put(jax.random.normal(k, (B, S, H, D), jnp.float32),
                              seq_sh) for k in ks]

        def sm(fn):
            return jax.jit(jax.shard_map(
                fn, mesh=mesh,
                in_specs=(P(None, "seq"), P(None, "seq"), P(None, "seq")),
                out_specs=(P(), P(None, "seq"), P(None, "seq"),
                           P(None, "seq")),
            ))

        full = sm(fb(functools.partial(ulysses_attention, causal=True),
                     axis="seq"))
        timeit(f"sp{sp}_full_ulysses", full, *qkv)

        def a2a_only(q, k, v):
            a2a = functools.partial(jax.lax.all_to_all, axis_name="seq",
                                    split_axis=2, concat_axis=1, tiled=True)
            back = functools.partial(jax.lax.all_to_all, axis_name="seq",
                                     split_axis=1, concat_axis=2, tiled=True)
            return back(a2a(q) + a2a(k) + a2a(v))

        timeit(f"sp{sp}_a2a_only", sm(fb(a2a_only, axis="seq")), *qkv)

        # local attention on head-sharded inputs: same per-device shapes
        # as inside ulysses after the reshard, zero collectives
        head_sh = NamedSharding(mesh, P(None, None, "seq"))
        qkv_h = [jax.device_put(jax.random.normal(k, (B, S, H, D),
                                                  jnp.float32), head_sh)
                 for k in ks]
        attn_local = jax.jit(jax.shard_map(
            fb(functools.partial(attention, causal=True), axis="seq"),
            mesh=mesh,
            in_specs=(P(None, None, "seq"),) * 3,
            out_specs=(P(), P(None, None, "seq"), P(None, None, "seq"),
                       P(None, None, "seq")),
        ))
        timeit(f"sp{sp}_attn_only_h{H // sp}", attn_local, *qkv_h)

    # mesh-free contrast: one device computing attention at each
    # heads-per-device value (same local shape as the sharded case).
    # The 4-D einsum path is timed EXPLICITLY here - ring.py attention()
    # now routes h==1 through the squeezed 3-D fix this diagnostic
    # motivated, so calling it would no longer reproduce the pathology.
    def generic_attn(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(float(D))
        mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    for h in (4, 2, 1):
        ks = jax.random.split(jax.random.key(5), 3)
        qkv1 = [jax.random.normal(k, (B, S, h, D), jnp.float32) for k in ks]
        timeit(f"single_dev_attn4d_h{h}", jax.jit(fb(generic_attn)), *qkv1)
        if h == 1:  # the shipped fix, same shape, for the A/B
            timeit("single_dev_attn_fixed_h1",
                   jax.jit(fb(functools.partial(attention, causal=True))),
                   *qkv1)

    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "ulysses_diag.json")
    with open(out_path, "w") as f:
        json.dump({"shape": {"batch": B, "seq": S, "heads": H, "head_dim": D},
                   "platform": jax.default_backend(),
                   "devices": len(dev), "steps": steps, "rows": rows},
                  f, indent=1)
    print(json.dumps({"wrote": out_path}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
