#!/usr/bin/env python
"""Render, diff, and gate training-dynamics JSONL streams (train/dynamics.py).

`lm_train.py --dynamics --dynamics-jsonl dyn.jsonl` appends one row per
step: global + per-layer gradient/parameter norms, update-to-weight
ratios, the gradient-noise-scale inputs, and non-finite provenance
(`bad_layer`). This tool is the operator/CI surface over that stream:

  # render the run: norm trajectory, worst layers, smoothed GNS readout
  python tools/dynamics.py dyn.jsonl

  # side-by-side comparison of two runs (per-metric relative drift)
  python tools/dynamics.py --diff before.jsonl after.jsonl

  # CI health gate (shardlint-style exit codes: 0 = healthy, 1 = gate
  # tripped, 2 = usage/input error). Without --baseline it gates run
  # invariants: no non-finite rows, update-to-weight ratio under
  # --max-upd-ratio, and final-vs-early grad-norm growth under
  # --max-growth. With --baseline it additionally gates relative drift
  # of the run summary (mean grad norm, mean update ratio, smoothed
  # noise scale) within --gate-frac.
  python tools/dynamics.py --check dyn.jsonl
  python tools/dynamics.py --check dyn.jsonl --baseline main.jsonl \
      [--gate-frac 0.5] [--max-upd-ratio 0.5] [--max-growth 10]

Malformed lines (truncated tail of a killed run, junk) are skipped and
counted, never fatal - but a stream with NO valid rows is an input
error. Semantics: docs/OBSERVABILITY.md "Training dynamics".
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def load_rows(path: str):
    """Parse a dynamics JSONL stream -> (rows sorted by step, n_malformed).

    A valid row is a JSON object with a numeric ``step`` and a ``layers``
    object (the decode_bundle shape). Anything else on a line counts as
    malformed and is skipped - a SIGKILLed run leaves a torn last line.
    """
    rows, malformed = [], 0
    try:
        f = open(path)
    except OSError as e:
        raise ValueError(f"{path}: {e}")
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                malformed += 1
                continue
            if (
                not isinstance(doc, dict)
                or not _is_num(doc.get("step"))
                or not isinstance(doc.get("layers"), dict)
            ):
                malformed += 1
                continue
            rows.append(doc)
    if not rows:
        raise ValueError(
            f"{path}: no dynamics rows"
            + (f" ({malformed} malformed line(s))" if malformed else "")
        )
    rows.sort(key=lambda r: r["step"])
    return rows, malformed


def gns_estimate(msq_small, sq_big, *, b_small, b_big):
    """Stdlib copy of train/dynamics.py gns_estimate (tools/ scripts do
    not import the package: its __init__ pulls in jax). Same contract:
    McCandlish simple estimator, None on degenerate inputs."""
    if not (
        _is_num(msq_small) and _is_num(sq_big)
        and math.isfinite(msq_small) and math.isfinite(sq_big)
    ):
        return None
    if not (_is_num(b_small) and _is_num(b_big)):
        return None
    if b_big <= b_small or b_small <= 0:
        return None
    grad_sq_true = (b_big * sq_big - b_small * msq_small) / (
        b_big - b_small
    )
    noise = (msq_small - sq_big) / (1.0 / b_small - 1.0 / b_big)
    if not (math.isfinite(grad_sq_true) and grad_sq_true > 0.0):
        return None
    return {
        "grad_sq_true": grad_sq_true,
        "noise_scale": noise,
        "crit_batch_size": noise / grad_sq_true,
    }


def _series(rows, key):
    return [r[key] for r in rows if _is_num(r.get(key))]


def _mean(xs):
    return sum(xs) / len(xs) if xs else None


def summarize(rows) -> dict:
    """Run-level summary of a dynamics stream (the render/diff/check
    payload). The smoothed GNS re-estimates from run-averaged
    msq_small/sq_big - far less noisy than any single step's readout."""
    grad = _series(rows, "grad_norm")
    upd = _series(rows, "upd_ratio_max")
    bad = [
        {"step": r["step"], "layer": r["bad_layer"]}
        for r in rows
        if r.get("bad_layer") is not None
    ]
    # early/late windows for the growth gate: first vs last 10% (>= 1 row)
    w = max(1, len(grad) // 10)
    early = _mean(grad[:w])
    late = _mean(grad[-w:])
    msq = _series(rows, "msq_small")
    sqb = _series(rows, "sq_big")
    b_small = next((r["b_small"] for r in rows if _is_num(r.get("b_small"))),
                   None)
    b_big = next((r["b_big"] for r in rows if _is_num(r.get("b_big"))), None)
    gns = (
        gns_estimate(_mean(msq), _mean(sqb), b_small=b_small, b_big=b_big)
        if msq and sqb else None
    )
    # final per-layer view from the last row that carries layers
    layers = {}
    for r in rows:
        for name, entry in r["layers"].items():
            if isinstance(entry, dict):
                layers[name] = entry  # last write wins (rows are sorted)
    return {
        "steps": len(rows),
        "step_range": [rows[0]["step"], rows[-1]["step"]],
        "grad_norm": {
            "first": grad[0] if grad else None,
            "last": grad[-1] if grad else None,
            "mean": _mean(grad),
            "max": max(grad) if grad else None,
            "early": early,
            "late": late,
        },
        "param_norm_last": (_series(rows, "param_norm") or [None])[-1],
        "upd_ratio_max": {
            "mean": _mean(upd),
            "max": max(upd) if upd else None,
        },
        "nonfinite_rows": bad,
        "gns": gns,
        "layers": layers,
    }


def _fmt(v, nd=4):
    if v is None:
        return "-"
    if isinstance(v, float) and (abs(v) >= 1e5 or (v != 0 and abs(v) < 1e-3)):
        return f"{v:.3e}"
    return f"{v:.{nd}f}" if isinstance(v, float) else str(v)


def render(summary: dict, *, title: str, malformed: int = 0,
           top: int = 5) -> str:
    s = summary
    lines = [title, "=" * len(title)]
    lo, hi = s["step_range"]
    lines.append(
        f"steps             {s['steps']} (step {lo} .. {hi})"
        + (f"   [{malformed} malformed line(s) skipped]" if malformed else "")
    )
    g = s["grad_norm"]
    lines.append(
        f"grad_norm         first {_fmt(g['first'])}  last {_fmt(g['last'])}"
        f"  mean {_fmt(g['mean'])}  max {_fmt(g['max'])}"
    )
    lines.append(f"param_norm (last) {_fmt(s['param_norm_last'])}")
    u = s["upd_ratio_max"]
    lines.append(
        f"upd_ratio_max     mean {_fmt(u['mean'])}  max {_fmt(u['max'])}"
    )
    if s["gns"] is not None:
        lines.append(
            f"GNS (smoothed)    noise_scale {_fmt(s['gns']['noise_scale'])}"
            f"  crit_batch_size {_fmt(s['gns']['crit_batch_size'], 1)} tokens"
        )
    else:
        lines.append("GNS (smoothed)    - (needs --accum-steps >= 2 with "
                     "--grad-sync end)")
    bad = s["nonfinite_rows"]
    if bad:
        lines.append(f"NON-FINITE        {len(bad)} row(s):")
        for b in bad[:top]:
            lines.append(f"  step {b['step']:>6}  first bad layer: "
                         f"{b['layer']}")
        if len(bad) > top:
            lines.append(f"  ... and {len(bad) - top} more")
    else:
        lines.append("non-finite rows   0")
    ranked = sorted(
        (
            (name, e)
            for name, e in s["layers"].items()
            if _is_num(e.get("grad_norm"))
        ),
        key=lambda kv: kv[1]["grad_norm"],
        reverse=True,
    )
    if ranked:
        lines.append(f"top {min(top, len(ranked))} layers by final "
                     "grad_norm (upd_ratio alongside):")
        width = max(len(name) for name, _ in ranked[:top])
        for name, e in ranked[:top]:
            lines.append(
                f"  {name:<{width}}  grad {_fmt(e['grad_norm'])}"
                f"  upd_ratio {_fmt(e.get('upd_ratio'))}"
            )
    return "\n".join(lines)


_DIFF_KEYS = (
    ("grad_norm mean", lambda s: s["grad_norm"]["mean"]),
    ("grad_norm last", lambda s: s["grad_norm"]["last"]),
    ("upd_ratio mean", lambda s: s["upd_ratio_max"]["mean"]),
    ("noise_scale", lambda s: (s["gns"] or {}).get("noise_scale")),
    ("crit_batch_size", lambda s: (s["gns"] or {}).get("crit_batch_size")),
    ("nonfinite rows", lambda s: float(len(s["nonfinite_rows"]))),
)


def diff(a: dict, b: dict, name_a: str, name_b: str) -> str:
    lines = [
        f"{'metric':<18} {name_a[:20]:>20} {name_b[:20]:>20} {'drift':>9}",
        "-" * 70,
    ]
    for label, get in _DIFF_KEYS:
        va, vb = get(a), get(b)
        drift = (
            f"{(vb - va) / abs(va):+.1%}"
            if _is_num(va) and _is_num(vb) and va
            else "-"
        )
        lines.append(
            f"{label:<18} {_fmt(va):>20} {_fmt(vb):>20} {drift:>9}"
        )
    return "\n".join(lines)


def check(summary: dict, *, baseline: dict | None, gate_frac: float,
          max_upd_ratio: float, max_growth: float) -> list:
    """Gate a run summary; returns the list of problems (empty = pass)."""
    problems = []
    bad = summary["nonfinite_rows"]
    if bad:
        first = bad[0]
        problems.append(
            f"{len(bad)} non-finite row(s); first at step {first['step']} "
            f"in layer {first['layer']!r}"
        )
    u = summary["upd_ratio_max"]["max"]
    if _is_num(u) and u > max_upd_ratio:
        problems.append(
            f"upd_ratio_max {u:.4g} exceeds --max-upd-ratio "
            f"{max_upd_ratio:g} (update >> weight: LR too hot or a "
            "layer diverging)"
        )
    g = summary["grad_norm"]
    if _is_num(g["early"]) and _is_num(g["late"]) and g["early"] > 0 \
            and g["late"] > g["early"] * max_growth:
        problems.append(
            f"grad_norm grew {g['late'] / g['early']:.1f}x from the "
            f"first to the last 10% of the run (--max-growth "
            f"{max_growth:g}): diverging"
        )
    if baseline is not None:
        for label, get in _DIFF_KEYS:
            if label == "nonfinite rows":
                continue
            va, vb = get(baseline), get(summary)
            if not (_is_num(va) and _is_num(vb)) or va == 0:
                continue
            drift = abs(vb - va) / abs(va)
            if drift > gate_frac:
                problems.append(
                    f"{label} drifted {drift:.1%} vs baseline "
                    f"({_fmt(va)} -> {_fmt(vb)}, --gate-frac "
                    f"{gate_frac:g})"
                )
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("stream", nargs="?", metavar="DYN.jsonl",
                   help="dynamics JSONL stream to render")
    p.add_argument("--diff", nargs=2, metavar=("A", "B"),
                   help="compare two streams side by side")
    p.add_argument("--check", metavar="DYN.jsonl",
                   help="gate a stream; exit 1 when a health gate trips")
    p.add_argument("--baseline", metavar="BASE.jsonl",
                   help="--check: also gate relative drift of the run "
                   "summary against this stream")
    p.add_argument("--gate-frac", type=float, default=0.5,
                   help="--check --baseline: max relative drift of any "
                   "summary metric (default 0.5)")
    p.add_argument("--max-upd-ratio", type=float, default=0.5,
                   help="--check: max allowed per-layer update-to-weight "
                   "ratio anywhere in the run (default 0.5; the healthy "
                   "band is ~1e-3)")
    p.add_argument("--max-growth", type=float, default=10.0,
                   help="--check: max allowed late/early grad-norm "
                   "growth factor (default 10)")
    p.add_argument("--top", type=int, default=5,
                   help="layers shown in the render ranking (default 5)")
    args = p.parse_args(argv)

    modes = sum(bool(x) for x in (args.stream, args.diff, args.check))
    if modes != 1:
        p.print_usage(sys.stderr)
        print("dynamics: give exactly one of DYN.jsonl, --diff A B, or "
              "--check DYN.jsonl", file=sys.stderr)
        return 2

    try:
        if args.diff:
            (ra, _), (rb, _) = (load_rows(x) for x in args.diff)
            print(diff(summarize(ra), summarize(rb),
                       os.path.basename(args.diff[0]),
                       os.path.basename(args.diff[1])))
            return 0
        if args.check:
            rows, malformed = load_rows(args.check)
            summary = summarize(rows)
            base = None
            if args.baseline:
                base_rows, _ = load_rows(args.baseline)
                base = summarize(base_rows)
            print(render(summary, title=f"Dynamics check: {args.check}",
                         malformed=malformed, top=args.top))
            problems = check(
                summary, baseline=base, gate_frac=args.gate_frac,
                max_upd_ratio=args.max_upd_ratio,
                max_growth=args.max_growth,
            )
            if problems:
                print(f"\nDYNAMICS CHECK FAILED ({len(problems)} "
                      "problem(s)):")
                for prob in problems:
                    print(f"  - {prob}")
                print("\nIf the drift is intended (new workload/LR), "
                      "regenerate the baseline stream and commit it with "
                      "the change that moved the dynamics.")
                return 1
            print("\ndynamics check OK")
            return 0
        rows, malformed = load_rows(args.stream)
        print(render(summarize(rows),
                     title=f"Training dynamics: {args.stream}",
                     malformed=malformed, top=args.top))
        return 0
    except ValueError as e:
        print(f"dynamics: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
