#!/usr/bin/env python
"""Fleet digital twin CLI (analysis/fleetsim.py): predict goodput for a
fleet you don't own, rank robustness policies, derive the optimal
checkpoint cadence, and validate the simulator against a measured run.

  # forward-simulate one policy over a synthetic Poisson failure trace
  python tools/fleetsim.py --procs 64 --failure-rate 0.02 --horizon-h 24 \
      --step-time 0.8 --checkpoint-every 200 \
      [--distributions dists.json] [--seed 0] [-o fleetsim.json]

  # rank a policy grid (repeatable --sweep KNOB=V1,V2,...; knobs may be
  # SimPolicy fields or the shared SupervisorPolicy fields)
  python tools/fleetsim.py --procs 64 --failure-rate 0.02 --horizon-h 24 \
      --sweep checkpoint_every_steps=50,200,800 --sweep max_restarts=2,8

  # optimal checkpoint cadence, cross-checked against Young/Daly
  python tools/fleetsim.py --procs 64 --failure-rate 0.02 --horizon-h 24 \
      --step-time 0.8 --checkpoint-write 12 --cadence-search

  # rank autoshard plans by goodput-under-failures (the second scoring
  # axis: cost-model step seconds x the failure process)
  python tools/fleetsim.py --plans distributed_neural_network_tpu/analysis/plans/lm_*.json \
      --procs 16 --failure-rate 0.05 --horizon-h 12 --hw tpu-v5e \
      --params 1e9 --tokens-per-step 5e5

  # closed-loop validation: replay the failure history a supervised run
  # recorded (run_record.json + records/gen{g}_rank{r}.json) through the
  # event model and assert bucket agreement within tolerance
  # (exit 0 = agree, 1 = prediction drift, 2 = usage/input error)
  python tools/fleetsim.py --validate svrun [--record OTHER.json] \
      [--ratio-tol 0.1] [--share-tol 0.1] [-o fleetsim.json]

Empirical inputs come from `tools/goodput.py --distributions` (restart
gaps, checkpoint saves, init/compile, measured step times); without
them the policy's fallback durations apply. Predicted records are
schema-compatible (`kind: "sim"`): render/diff/gate them with
tools/goodput.py, and drop `-o fleetsim.json` into a run dir for
tools/live_top.py's predicted-vs-actual line.
Semantics: docs/OBSERVABILITY.md "Fleet digital twin".

SERVE MODE (--serve): the serving fleet's twin - same contract, the
request lifecycle instead of the training loop.

  # forward-simulate a Poisson load against a servelint manifest
  python tools/fleetsim.py --serve --rate 6 --requests 200 \
      --manifest distributed_neural_network_tpu/analysis/serve/serve_bf16.json \
      --hw cpu-host --slo ttft_p99=0.5 [-o fleetsim_serve.json]

  # the DYNAMIC replica answer next to cost.replicas_for_target's
  # static floor (dynamic >= static by construction)
  python tools/fleetsim.py --serve --manifest ... --hw cpu-host \
      --replicas-for 6,ttft_p99=0.5

  # rank autoscaler/admission policy variants by SLO-attained
  # completions per replica up-second
  python tools/fleetsim.py --serve --rate 6 --requests 200 --manifest ... \
      --slo ttft_p99=0.5 --sweep max_batch=2,4,8 --sweep queue_high=4,16

  # closed-loop validation against a measured serve-smoke run dir
  # (serve_record.json + reqs.json + client_reqs.jsonl [+ arrivals.json])
  python tools/fleetsim.py --serve --validate rundir \
      [--ratio-tol 0.15] [--share-tol 0.15] [--pct-tol 0.5]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from distributed_neural_network_tpu.analysis import fleetsim as fs  # noqa: E402
from distributed_neural_network_tpu.train.supervisor import (  # noqa: E402
    SupervisorPolicy,
)
from distributed_neural_network_tpu.utils.goodput import (  # noqa: E402
    read_record,
    render_record,
    validate_record,
)


def _build_policy(args) -> fs.SimPolicy:
    sup = SupervisorPolicy(
        nprocs=args.procs,
        min_procs=args.min_procs,
        max_restarts=args.max_restarts,
        restart_backoff_s=args.restart_backoff,
        backoff_cap_s=args.backoff_cap,
        grace_s=args.grace,
        grow_after_s=args.grow_after,
    )
    return fs.SimPolicy(
        supervisor=sup,
        checkpoint_every_steps=args.checkpoint_every,
        step_time_s=args.step_time,
        step_overhead_s=args.step_overhead,
        tokens_per_step=args.tokens_per_step,
        init_s=args.init_s,
        compile_s=args.compile_s,
        checkpoint_write_s=args.checkpoint_write,
        restart_gap_s=args.restart_gap,
    )


def _parse_sweep(pairs) -> dict:
    out = {}
    for pair in pairs or ():
        if "=" not in pair:
            raise ValueError(
                f"--sweep wants KNOB=V1,V2,..., got {pair!r}"
            )
        knob, vals = pair.split("=", 1)
        parsed = []
        for v in vals.split(","):
            v = v.strip()
            try:
                parsed.append(int(v))
            except ValueError:
                try:
                    parsed.append(float(v))
                except ValueError:
                    raise ValueError(
                        f"--sweep {pair!r}: {v!r} is not a number"
                    )
        out[knob.strip()] = parsed
    return out


def _write_out(path: str | None, rec: dict) -> None:
    if not path:
        return
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"(fleetsim: predicted record -> {path})")


def _load_rank_records(run_dir: str) -> list:
    d = os.path.join(run_dir, "records")
    if not os.path.isdir(d):
        d = run_dir
    out = []
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json") or name == "run_record.json":
            continue
        try:
            with open(os.path.join(d, name)) as f:
                out.append(validate_record(json.load(f), name))
        except (OSError, ValueError):
            continue  # torn write-through tail / non-record file
    return out


def run_validate(args) -> int:
    run_dir = args.validate
    record_path = args.record or os.path.join(run_dir, "run_record.json")
    try:
        fleet = read_record(record_path)
    except (OSError, ValueError) as e:
        print(f"fleetsim: cannot read the measured fleet record: {e}",
              file=sys.stderr)
        return 2
    ranks = _load_rank_records(run_dir)
    if not ranks:
        print(
            f"fleetsim: no per-worker records under {run_dir} "
            "(expected records/gen{g}_rank{r}.json write-through "
            "records from a supervised run)", file=sys.stderr,
        )
        return 2
    pred = fs.predict_from_ledger(fleet, ranks)
    problems = fs.compare_records(
        pred, fleet, ratio_tol=args.ratio_tol, share_tol=args.share_tol
    )
    print(render_record(
        pred, title=f"Fleetsim replay of {record_path} "
        f"({len(ranks)} rank record(s))"
    ))
    print()
    print(render_record(fleet, title="Measured ledger record"))
    _write_out(args.json_out, pred)
    if problems:
        print(f"\nFLEETSIM VALIDATION FAILED ({len(problems)} "
              "disagreement(s)):")
        for prob in problems:
            print(f"  - {prob}")
        print("\nThe simulator's event model no longer reproduces the "
              "measured ledger - fix the drift (or loosen the tolerance "
              "with --ratio-tol/--share-tol if the run's accounting "
              "legitimately changed).")
        return 1
    print(f"\nfleetsim validation OK: prediction within "
          f"ratio-tol {args.ratio_tol:g} / share-tol {args.share_tol:g} "
          "of the measured ledger")
    return 0


def run_cadence_search(args, policy, dists) -> int:
    res = fs.cadence_search(
        policy, dists,
        rate_per_chip_per_h=args.failure_rate,
        horizon_s=args.horizon_h * 3600.0,
        seeds=tuple(range(args.seed, args.seed + args.seeds)),
    )
    if not res["results"]:
        print("fleetsim: empty cadence grid (is --failure-rate 0?)",
              file=sys.stderr)
        return 2
    yd = res["young_daly"]
    print(f"Checkpoint-cadence search ({len(res['results'])} candidates, "
          f"group MTBF {yd['mtbf_s']:,.0f}s, checkpoint "
          f"{yd['checkpoint_s']:,.1f}s):")
    print(f"  {'every':>8} {'interval':>12} {'eff-goodput':>12}")
    best = res["best"]
    for k, tau, ratio in res["results"]:
        tag = "  <- best" if (k, tau, ratio) == best else ""
        print(f"  {k:>8} {tau:>11,.1f}s {ratio:>11.2%}{tag}")
    print(
        f"  Young/Daly sqrt(2*delta*MTBF) = {yd['interval_s']:,.1f}s "
        f"(cadence {yd['cadence_steps']}); simulated best "
        f"{best[1]:,.1f}s = {100.0 * best[1] / yd['interval_s']:.0f}% "
        "of the first-order optimum"
    )
    return 0


def run_plans(args, policy, dists) -> int:
    paths = []
    for pat in args.plans:
        hits = sorted(glob.glob(pat))
        paths.extend(hits if hits else [pat])
    docs = []
    for path in paths:
        with open(path) as f:
            docs.append(json.load(f))
    flops = args.flops_per_step
    if not flops and args.params:
        from distributed_neural_network_tpu.analysis.cost import (
            dense_step_flops,
        )

        flops = dense_step_flops(args.params, args.tokens_per_step)
    from distributed_neural_network_tpu.analysis.cost import (
        HARDWARE_MODELS,
    )

    if args.hw not in HARDWARE_MODELS:
        print(f"fleetsim: unknown --hw {args.hw!r} (known: "
              f"{', '.join(sorted(HARDWARE_MODELS))})", file=sys.stderr)
        return 2
    ranked = fs.rank_plans_by_goodput(
        docs, policy, dists,
        hw=HARDWARE_MODELS[args.hw], flops_per_step=flops,
        rate_per_chip_per_h=args.failure_rate,
        horizon_s=args.horizon_h * 3600.0,
        seeds=tuple(range(args.seed, args.seed + args.seeds)),
    )
    print(f"Plans ranked by predicted goodput-under-failures "
          f"({args.procs} procs, {args.failure_rate:g}/chip/h, "
          f"{args.horizon_h:g}h horizon, hw {args.hw}; metric = "
          "surviving steps per capacity-second):")
    for i, row in enumerate(ranked):
        print(f"  #{i + 1} {row['plan']:<28} "
              f"{row['progress_steps_per_cap_s']:,.3f} steps/cap-s  "
              f"step {row['step_s'] * 1e3:,.3f} ms  "
              f"eff-goodput {row['effective_goodput_ratio']:.2%}  "
              f"(bytes-score {row['score']:,})"
              + ("  [ABORTED]" if row["aborted"] else ""))
        print(f"      {row['step_why']}")
    return 0


def _parse_slo(pairs) -> dict:
    out = {}
    for pair in pairs or ():
        if "=" not in pair:
            raise ValueError(f"--slo wants KEY=SECONDS (e.g. "
                             f"ttft_p99=0.5), got {pair!r}")
        k, v = pair.split("=", 1)
        out[k.strip()] = float(v)
    return out


def _read_json(path: str):
    with open(path) as f:
        return json.load(f)


def _load_serve_run_dir(run_dir: str, record_path: str | None):
    """(measured_record, request_details, client_rows, arrivals) out of
    a serve-smoke run dir."""
    record_path = record_path or os.path.join(run_dir, "serve_record.json")
    measured = read_record(record_path)
    details = []
    for name in ("reqs.json", "requests.json"):
        path = os.path.join(run_dir, name)
        if os.path.exists(path):
            doc = _read_json(path)
            details = list(doc.get("recent") or []) if isinstance(doc, dict) \
                else list(doc)
            break
    rows = []
    for name in ("client_reqs.jsonl", "client_requests.jsonl"):
        path = os.path.join(run_dir, name)
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail
            break
    arrivals = None
    apath = os.path.join(run_dir, "arrivals.json")
    if os.path.exists(apath):
        arrivals = _read_json(apath)
    return measured, details, rows, arrivals


def run_serve_validate(args) -> int:
    run_dir = args.validate
    try:
        measured, details, rows, arrivals = _load_serve_run_dir(
            run_dir, args.record
        )
    except (OSError, ValueError) as e:
        print(f"fleetsim: cannot load the serve run dir: {e}",
              file=sys.stderr)
        return 2
    done = [d for d in details if d.get("state") == "done"]
    if not done:
        print(
            f"fleetsim: no finished per-request records under {run_dir} "
            "(expected reqs.json - a GET /v1/requests?full=1 dump - "
            "next to serve_record.json)", file=sys.stderr,
        )
        return 2
    try:
        pred, reqdoc = fs.predict_serve_from_run(
            measured, done, arrivals=arrivals, client_rows=rows,
            seed=args.seed,
        )
    except (OSError, ValueError) as e:
        print(f"fleetsim: {e}", file=sys.stderr)
        return 2
    problems = fs.compare_records(
        pred, measured, ratio_tol=args.ratio_tol, share_tol=args.share_tol
    )
    problems += fs.compare_serve_percentiles(
        reqdoc["recent"], done, tol=args.pct_tol
    )
    print(render_record(
        pred, title=f"Fleetsim serve replay of {run_dir} "
        f"({len(done)} measured request(s), "
        f"{pred['sim']['n_arrivals']} arrival(s) replayed)"
    ))
    print()
    print(render_record(measured, title="Measured serve ledger record"))
    print("\n  predicted vs measured percentiles:")
    for key in fs.SERVE_PCT_KEYS:
        metric, _, qs = key.partition("_p")
        dp = (pred["predicted"].get(metric) or {}).get(f"p{qs}")
        dm = fs._serve_decompose(done, metric, float(qs) / 100.0)
        pv = dp["value"] if dp else None
        mv = dm["value"] if dm else None
        if pv is None or mv is None:
            continue
        print(f"    {key:<10} predicted {pv:>9.4f}s  "
              f"measured {mv:>9.4f}s  "
              f"(dominant: {dp['dominant']} / {dm['dominant']})")
    _write_out(args.json_out, pred)
    if args.requests_out:
        with open(args.requests_out, "w") as f:
            json.dump(reqdoc, f, indent=1)
        print(f"(fleetsim: simulated requests -> {args.requests_out})")
    if problems:
        print(f"\nFLEETSIM SERVE VALIDATION FAILED ({len(problems)} "
              "disagreement(s)):")
        for prob in problems:
            print(f"  - {prob}")
        print("\nThe serve twin's event model no longer reproduces the "
              "measured run - fix the drift (or loosen --ratio-tol/"
              "--share-tol/--pct-tol if the accounting legitimately "
              "changed).")
        return 1
    print(f"\nfleetsim serve validation OK: prediction within "
          f"ratio-tol {args.ratio_tol:g} / share-tol {args.share_tol:g} "
          f"/ pct-tol {args.pct_tol:g} of the measured run")
    return 0


def run_serve(args) -> int:
    manifest = _read_json(args.manifest) if args.manifest else None
    dists = (
        fs.Distributions.load(args.distributions)
        if args.distributions else None
    )
    slo = _parse_slo(args.slo)
    if args.replicas_for:
        if manifest is None:
            print("fleetsim: --replicas-for needs --manifest (a "
                  "servelint manifest prices the static floor)",
                  file=sys.stderr)
            return 2
        parts = [x.strip() for x in args.replicas_for.split(",") if x]
        rate = float(parts[0])
        rf_slo = _parse_slo(parts[1:]) or slo
        if not rf_slo:
            print("fleetsim: --replicas-for RATE,ttft_p99=X wants at "
                  "least one SLO gate", file=sys.stderr)
            return 2
        res = fs.replicas_for_dynamic(
            manifest, hw=args.hw, rate_rps=rate, slo=rf_slo,
            mean_new_tokens=args.max_new, prompt_len=args.prompt_lens[0],
            dists=dists, n_requests=args.requests, seed=args.seed,
            max_replicas=args.max_replicas or 64,
        )
        st, dy = res["static"], res["dynamic"]
        print(f"Replica planning at {rate:g} req/s, SLO "
              + ", ".join(f"{k}<={v:g}s" for k, v in sorted(rf_slo.items()))
              + f" (hw {args.hw}):")
        print(f"  static floor (cost.replicas_for_target, no queueing): "
              f"{st['replicas']} replica(s), "
              f"util {st['utilization_at_n']:.0%}"
              + ("" if st.get("feasible", True)
                 else f"  [INFEASIBLE: {st.get('why')}]"))
        print(f"  dynamic answer (serve twin, queueing simulated):    "
              f"{dy['replicas']} replica(s)"
              + ("" if dy["met"] else f"  [SLO NOT MET: {dy.get('why')}]"))
        for row in res["curve"]:
            gates = "  ".join(
                f"{k}={g['value']:.3f}s{'' if g['met'] else '!'}"
                for k, g in sorted(row["gates"].items())
                if g["value"] is not None
            )
            print(f"    n={row['replicas']:<3} "
                  f"{'meets SLO' if row['met'] else 'violates '}  {gates}")
        if args.json_out:
            _write_out(args.json_out, res)
        return 0
    # arrivals
    if args.arrival_trace:
        arrivals = fs.load_arrivals(_read_json(args.arrival_trace))
    else:
        if not args.rate:
            print("fleetsim: --serve wants --rate RPS (or "
                  "--arrival-trace IN.json)", file=sys.stderr)
            return 2
        arrivals = fs.synthesize_arrivals(
            args.rate, n_requests=args.requests,
            horizon_s=args.horizon or None,
            prompt_lens=tuple(args.prompt_lens), max_new=args.max_new,
            seed=args.seed, dists=dists,
        )
    if manifest is not None:
        policy = fs.ServePolicy.from_manifest(manifest)
    else:
        policy = fs.ServePolicy()
    policy = policy.with_(
        replicas=args.replicas,
        max_replicas=args.max_replicas,
        autoscale_every_s=args.autoscale_every,
        queue_high=args.queue_high,
        provision_s=args.provision_s,
        restart_gap_s=args.restart_gap,
        slo=slo,
    )
    trace = ()
    if args.failure_rate > 0 and args.serve_failures:
        trace = fs.synthesize_failure_trace(
            max(args.replicas, 1),
            rate_per_chip_per_h=args.failure_rate,
            horizon_s=args.horizon or 3600.0,
            seed=args.seed,
        )
    if args.sweep:
        grid = fs.policy_variants(policy, _parse_sweep(args.sweep))
        ranked = fs.rank_serve_policies(
            grid, rate_rps=args.rate, arrivals=arrivals, dists=dists,
            manifest=manifest, hw=args.hw, n_requests=args.requests,
            failure_rate_per_replica_per_h=(
                args.failure_rate if args.serve_failures else 0.0
            ),
            horizon_s=args.horizon or 3600.0,
            seeds=tuple(range(args.seed, args.seed + args.seeds)),
        )
        print(f"Serve policies ranked by SLO-attained completions per "
              f"capacity-second ({len(ranked)} candidate(s), "
              f"{args.seeds} seed(s) averaged):")
        for i, row in enumerate(ranked):
            print(f"  #{i + 1} {row['policy']:<44} "
                  f"{row['slo_per_capacity_s']:.4f}/cap-s  "
                  f"attain {row['slo_attainment']:.2%}  "
                  f"done {row['completed']}  rej {row['rejected']}  "
                  f"preempt {row['preemptions']}")
        return 0
    rec, reqdoc = fs.simulate_serve(
        policy, arrivals, dists=dists, manifest=manifest, hw=args.hw,
        failure_trace=trace, horizon_s=args.horizon or None,
        seed=args.seed,
    )
    print(render_record(
        rec, title=f"Fleetsim serve prediction ({len(arrivals)} "
        f"arrival(s), {rec['replicas']} replica(s), "
        f"pricing {rec['sim']['pricing']}, seed {args.seed})"
    ))
    r = rec["requests"]
    print(f"  requests: {r['completed']}/{r['offered']} completed, "
          f"{r['rejected']} rejected, {r['rejected_too_long']} too-long, "
          f"{r['preemptions']} preemption(s), "
          f"{r['router_retries']} router retry(s); "
          f"SLO attainment {rec['slo_attainment']:.2%}")
    for metric in ("ttft", "e2e"):
        for q, d in sorted((rec["predicted"].get(metric) or {}).items()):
            print(f"  predicted {metric}_{q}: {d['value']:.4f}s "
                  f"(dominant: {d['dominant']})")
    _write_out(args.json_out, rec)
    if args.requests_out:
        with open(args.requests_out, "w") as f:
            json.dump(reqdoc, f, indent=1)
        print(f"(fleetsim: simulated requests -> {args.requests_out}; "
              "render with tools/request_trace.py)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    mode = p.add_argument_group("modes (default: forward-simulate once)")
    mode.add_argument("--validate", metavar="RUN_DIR",
                      help="replay a supervised run's measured failure "
                      "history and assert sim-vs-ledger agreement")
    mode.add_argument("--cadence-search", action="store_true",
                      help="search checkpoint cadences, cross-checked "
                      "against the Young/Daly optimum")
    mode.add_argument("--sweep", action="append", metavar="KNOB=V1,V2",
                      help="rank a policy grid over these knob values "
                      "(repeatable; SimPolicy or SupervisorPolicy "
                      "fields)")
    mode.add_argument("--plans", nargs="+", metavar="PLAN.json",
                      help="rank autoshard plan manifests by "
                      "goodput-under-failures (cost-model step seconds)")
    pol = p.add_argument_group("policy (shared SupervisorPolicy + workload)")
    pol.add_argument("--procs", type=int, default=2)
    pol.add_argument("--min-procs", type=int, default=1)
    pol.add_argument("--max-restarts", type=int, default=3)
    pol.add_argument("--restart-backoff", type=float, default=1.0)
    pol.add_argument("--backoff-cap", type=float, default=30.0)
    pol.add_argument("--grace", type=float, default=10.0)
    pol.add_argument("--grow-after", type=float, default=0.0)
    pol.add_argument("--checkpoint-every", type=int, default=0,
                     metavar="STEPS")
    pol.add_argument("--step-time", type=float, default=None, metavar="SEC",
                     help="steady step seconds (default: the "
                     "distributions' measured mean, else 1.0)")
    pol.add_argument("--step-overhead", type=float, default=None,
                     metavar="SEC", help="per-step host overhead "
                     "(default: the distributions' derived value, else 0)")
    pol.add_argument("--tokens-per-step", type=float, default=0.0)
    pol.add_argument("--init-s", type=float, default=5.0)
    pol.add_argument("--compile-s", type=float, default=10.0)
    pol.add_argument("--checkpoint-write", type=float, default=1.0,
                     metavar="SEC")
    pol.add_argument("--restart-gap", type=float, default=10.0,
                     metavar="SEC")
    tr = p.add_argument_group("failure trace")
    tr.add_argument("--chips", type=int, default=None,
                    help="failing machines (default: --procs)")
    tr.add_argument("--failure-rate", type=float, default=0.01,
                    metavar="PER_CHIP_PER_H")
    tr.add_argument("--preempt-fraction", type=float, default=0.0)
    tr.add_argument("--horizon-h", type=float, default=24.0)
    tr.add_argument("--target-steps", type=int, default=None)
    tr.add_argument("--seed", type=int, default=0)
    tr.add_argument("--seeds", type=int, default=2,
                    help="seeds averaged in sweep/cadence/plan modes")
    io = p.add_argument_group("inputs / outputs")
    io.add_argument("--distributions", metavar="DISTS.json",
                    help="empirical distributions from tools/goodput.py "
                    "--distributions")
    io.add_argument("--record", metavar="RECORD.json",
                    help="--validate: measured fleet record override "
                    "(default RUN_DIR/run_record.json)")
    io.add_argument("--ratio-tol", type=float, default=0.1)
    io.add_argument("--share-tol", type=float, default=0.1)
    io.add_argument("--hw", default="tpu-v5e",
                    help="--plans: hardware model for step pricing")
    io.add_argument("--params", type=float, default=0.0,
                    help="--plans: parameter count for 6*P*T step flops")
    io.add_argument("--flops-per-step", type=float, default=0.0)
    io.add_argument("-o", "--json-out", metavar="OUT.json",
                    help="write the predicted record (drop fleetsim.json "
                    "into a run dir for live_top's predicted line, "
                    "fleetsim_serve.json for the serve pane)")
    sv = p.add_argument_group("serve mode (--serve)")
    sv.add_argument("--serve", action="store_true",
                    help="simulate the SERVING fleet (request lifecycle) "
                    "instead of the training loop")
    sv.add_argument("--rate", type=float, default=0.0, metavar="RPS",
                    help="open-loop Poisson arrival rate")
    sv.add_argument("--requests", type=int, default=200,
                    help="arrivals to synthesize (with --rate)")
    sv.add_argument("--horizon", type=float, default=0.0, metavar="SEC",
                    help="serve horizon seconds (optional cap)")
    sv.add_argument("--arrival-trace", metavar="IN.json",
                    help="replay a recorded arrival stream "
                    "(tools/loadgen.py --arrival-trace output)")
    sv.add_argument("--manifest", metavar="MANIFEST.json",
                    help="servelint manifest: engine/kv geometry + "
                    "roofline tick pricing (analysis/serve/*.json)")
    sv.add_argument("--prompt-lens", type=lambda s: [
                        int(x) for x in s.split(",") if x
                    ], default=[4, 8, 16], metavar="L1,L2,...")
    sv.add_argument("--max-new", type=int, default=16)
    sv.add_argument("--replicas", type=int, default=1)
    sv.add_argument("--max-replicas", type=int, default=0,
                    help="autoscaler ceiling (0 = --replicas, "
                    "autoscaling capped off)")
    sv.add_argument("--autoscale-every", type=float, default=0.0,
                    metavar="SEC", help="autoscale_decision replay "
                    "cadence (0 = off)")
    sv.add_argument("--queue-high", type=int, default=8)
    sv.add_argument("--provision-s", type=float, default=10.0,
                    help="scale-up decision -> replica live")
    sv.add_argument("--serve-failures", action="store_true",
                    help="draw replica failures at --failure-rate "
                    "per replica per hour")
    sv.add_argument("--slo", action="append", metavar="KEY=SEC",
                    help="SLO gate, e.g. ttft_p99=0.5 (repeatable)")
    sv.add_argument("--replicas-for", metavar="RATE,ttft_p99=X",
                    help="dynamic replica answer for a rate + SLO, "
                    "reported next to the static floor")
    sv.add_argument("--pct-tol", type=float, default=0.5,
                    help="--serve --validate: relative TTFT/E2E "
                    "percentile tolerance")
    sv.add_argument("--requests-out", metavar="OUT.json",
                    help="write the simulated per-request document "
                    "(tools/request_trace.py renders it)")
    args = p.parse_args(argv)

    try:
        if args.serve:
            if args.validate:
                return run_serve_validate(args)
            return run_serve(args)
        if args.validate:
            return run_validate(args)
        dists = (
            fs.Distributions.load(args.distributions)
            if args.distributions else fs.Distributions()
        )
        if args.step_overhead is None:
            args.step_overhead = dists.step_overhead_s(0.0)
        if args.step_time is None:
            # the measured step-time distribution wins over the default
            args.step_time = dists.mean("steady_step", 1.0)
        policy = _build_policy(args)
        if args.cadence_search:
            return run_cadence_search(args, policy, dists)
        if args.plans:
            return run_plans(args, policy, dists)
        if args.sweep:
            grid = fs.policy_variants(policy, _parse_sweep(args.sweep))
            ranked = fs.rank_policies(
                grid, dists,
                n_chips=args.chips or args.procs,
                rate_per_chip_per_h=args.failure_rate,
                horizon_s=args.horizon_h * 3600.0,
                preempt_fraction=args.preempt_fraction,
                seeds=tuple(range(args.seed, args.seed + args.seeds)),
            )
            print(f"Policies ranked by effective goodput "
                  f"({len(ranked)} candidate(s), "
                  f"{args.seeds} seed(s) averaged):")
            for i, row in enumerate(ranked):
                print(f"  #{i + 1} {row['label']:<44} "
                      f"eff {row['effective_goodput_ratio']:.2%}  "
                      f"ledger {row['goodput_ratio']:.2%}"
                      + ("  [ABORTED]" if row["aborted"] else ""))
            _write_out(args.json_out, ranked[0]["record"])
            return 0
        trace = fs.synthesize_failure_trace(
            args.chips or args.procs,
            rate_per_chip_per_h=args.failure_rate,
            horizon_s=args.horizon_h * 3600.0,
            seed=args.seed,
            preempt_fraction=args.preempt_fraction,
        )
        rec = fs.simulate(
            policy, trace, dists,
            horizon_s=args.horizon_h * 3600.0,
            target_steps=args.target_steps, seed=args.seed,
        )
        m = rec["metrics"]
        print(render_record(
            rec, title=f"Fleetsim prediction ({args.procs} procs, "
            f"{len(trace)} failure event(s), seed {args.seed})"
        ))
        print(f"  effective goodput {m['effective_goodput_ratio']:.2%} "
              f"({m['lost_steps']} lost step(s), "
              f"{m['restarts_used']} restart(s), "
              f"{m['generations']} generation(s))"
              + (f"; ABORTED: {m['abort_reason']}"
                 if m["aborted"] else ""))
        _write_out(args.json_out, rec)
        return 0
    except (OSError, ValueError) as e:
        print(f"fleetsim: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
