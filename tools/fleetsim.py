#!/usr/bin/env python
"""Fleet digital twin CLI (analysis/fleetsim.py): predict goodput for a
fleet you don't own, rank robustness policies, derive the optimal
checkpoint cadence, and validate the simulator against a measured run.

  # forward-simulate one policy over a synthetic Poisson failure trace
  python tools/fleetsim.py --procs 64 --failure-rate 0.02 --horizon-h 24 \
      --step-time 0.8 --checkpoint-every 200 \
      [--distributions dists.json] [--seed 0] [-o fleetsim.json]

  # rank a policy grid (repeatable --sweep KNOB=V1,V2,...; knobs may be
  # SimPolicy fields or the shared SupervisorPolicy fields)
  python tools/fleetsim.py --procs 64 --failure-rate 0.02 --horizon-h 24 \
      --sweep checkpoint_every_steps=50,200,800 --sweep max_restarts=2,8

  # optimal checkpoint cadence, cross-checked against Young/Daly
  python tools/fleetsim.py --procs 64 --failure-rate 0.02 --horizon-h 24 \
      --step-time 0.8 --checkpoint-write 12 --cadence-search

  # rank autoshard plans by goodput-under-failures (the second scoring
  # axis: cost-model step seconds x the failure process)
  python tools/fleetsim.py --plans distributed_neural_network_tpu/analysis/plans/lm_*.json \
      --procs 16 --failure-rate 0.05 --horizon-h 12 --hw tpu-v5e \
      --params 1e9 --tokens-per-step 5e5

  # closed-loop validation: replay the failure history a supervised run
  # recorded (run_record.json + records/gen{g}_rank{r}.json) through the
  # event model and assert bucket agreement within tolerance
  # (exit 0 = agree, 1 = prediction drift, 2 = usage/input error)
  python tools/fleetsim.py --validate svrun [--record OTHER.json] \
      [--ratio-tol 0.1] [--share-tol 0.1] [-o fleetsim.json]

Empirical inputs come from `tools/goodput.py --distributions` (restart
gaps, checkpoint saves, init/compile, measured step times); without
them the policy's fallback durations apply. Predicted records are
schema-compatible (`kind: "sim"`): render/diff/gate them with
tools/goodput.py, and drop `-o fleetsim.json` into a run dir for
tools/live_top.py's predicted-vs-actual line.
Semantics: docs/OBSERVABILITY.md "Fleet digital twin".
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from distributed_neural_network_tpu.analysis import fleetsim as fs  # noqa: E402
from distributed_neural_network_tpu.train.supervisor import (  # noqa: E402
    SupervisorPolicy,
)
from distributed_neural_network_tpu.utils.goodput import (  # noqa: E402
    read_record,
    render_record,
    validate_record,
)


def _build_policy(args) -> fs.SimPolicy:
    sup = SupervisorPolicy(
        nprocs=args.procs,
        min_procs=args.min_procs,
        max_restarts=args.max_restarts,
        restart_backoff_s=args.restart_backoff,
        backoff_cap_s=args.backoff_cap,
        grace_s=args.grace,
        grow_after_s=args.grow_after,
    )
    return fs.SimPolicy(
        supervisor=sup,
        checkpoint_every_steps=args.checkpoint_every,
        step_time_s=args.step_time,
        step_overhead_s=args.step_overhead,
        tokens_per_step=args.tokens_per_step,
        init_s=args.init_s,
        compile_s=args.compile_s,
        checkpoint_write_s=args.checkpoint_write,
        restart_gap_s=args.restart_gap,
    )


def _parse_sweep(pairs) -> dict:
    out = {}
    for pair in pairs or ():
        if "=" not in pair:
            raise ValueError(
                f"--sweep wants KNOB=V1,V2,..., got {pair!r}"
            )
        knob, vals = pair.split("=", 1)
        parsed = []
        for v in vals.split(","):
            v = v.strip()
            try:
                parsed.append(int(v))
            except ValueError:
                try:
                    parsed.append(float(v))
                except ValueError:
                    raise ValueError(
                        f"--sweep {pair!r}: {v!r} is not a number"
                    )
        out[knob.strip()] = parsed
    return out


def _write_out(path: str | None, rec: dict) -> None:
    if not path:
        return
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    print(f"(fleetsim: predicted record -> {path})")


def _load_rank_records(run_dir: str) -> list:
    d = os.path.join(run_dir, "records")
    if not os.path.isdir(d):
        d = run_dir
    out = []
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json") or name == "run_record.json":
            continue
        try:
            with open(os.path.join(d, name)) as f:
                out.append(validate_record(json.load(f), name))
        except (OSError, ValueError):
            continue  # torn write-through tail / non-record file
    return out


def run_validate(args) -> int:
    run_dir = args.validate
    record_path = args.record or os.path.join(run_dir, "run_record.json")
    try:
        fleet = read_record(record_path)
    except (OSError, ValueError) as e:
        print(f"fleetsim: cannot read the measured fleet record: {e}",
              file=sys.stderr)
        return 2
    ranks = _load_rank_records(run_dir)
    if not ranks:
        print(
            f"fleetsim: no per-worker records under {run_dir} "
            "(expected records/gen{g}_rank{r}.json write-through "
            "records from a supervised run)", file=sys.stderr,
        )
        return 2
    pred = fs.predict_from_ledger(fleet, ranks)
    problems = fs.compare_records(
        pred, fleet, ratio_tol=args.ratio_tol, share_tol=args.share_tol
    )
    print(render_record(
        pred, title=f"Fleetsim replay of {record_path} "
        f"({len(ranks)} rank record(s))"
    ))
    print()
    print(render_record(fleet, title="Measured ledger record"))
    _write_out(args.json_out, pred)
    if problems:
        print(f"\nFLEETSIM VALIDATION FAILED ({len(problems)} "
              "disagreement(s)):")
        for prob in problems:
            print(f"  - {prob}")
        print("\nThe simulator's event model no longer reproduces the "
              "measured ledger - fix the drift (or loosen the tolerance "
              "with --ratio-tol/--share-tol if the run's accounting "
              "legitimately changed).")
        return 1
    print(f"\nfleetsim validation OK: prediction within "
          f"ratio-tol {args.ratio_tol:g} / share-tol {args.share_tol:g} "
          "of the measured ledger")
    return 0


def run_cadence_search(args, policy, dists) -> int:
    res = fs.cadence_search(
        policy, dists,
        rate_per_chip_per_h=args.failure_rate,
        horizon_s=args.horizon_h * 3600.0,
        seeds=tuple(range(args.seed, args.seed + args.seeds)),
    )
    if not res["results"]:
        print("fleetsim: empty cadence grid (is --failure-rate 0?)",
              file=sys.stderr)
        return 2
    yd = res["young_daly"]
    print(f"Checkpoint-cadence search ({len(res['results'])} candidates, "
          f"group MTBF {yd['mtbf_s']:,.0f}s, checkpoint "
          f"{yd['checkpoint_s']:,.1f}s):")
    print(f"  {'every':>8} {'interval':>12} {'eff-goodput':>12}")
    best = res["best"]
    for k, tau, ratio in res["results"]:
        tag = "  <- best" if (k, tau, ratio) == best else ""
        print(f"  {k:>8} {tau:>11,.1f}s {ratio:>11.2%}{tag}")
    print(
        f"  Young/Daly sqrt(2*delta*MTBF) = {yd['interval_s']:,.1f}s "
        f"(cadence {yd['cadence_steps']}); simulated best "
        f"{best[1]:,.1f}s = {100.0 * best[1] / yd['interval_s']:.0f}% "
        "of the first-order optimum"
    )
    return 0


def run_plans(args, policy, dists) -> int:
    paths = []
    for pat in args.plans:
        hits = sorted(glob.glob(pat))
        paths.extend(hits if hits else [pat])
    docs = []
    for path in paths:
        with open(path) as f:
            docs.append(json.load(f))
    flops = args.flops_per_step
    if not flops and args.params:
        from distributed_neural_network_tpu.analysis.cost import (
            dense_step_flops,
        )

        flops = dense_step_flops(args.params, args.tokens_per_step)
    from distributed_neural_network_tpu.analysis.cost import (
        HARDWARE_MODELS,
    )

    if args.hw not in HARDWARE_MODELS:
        print(f"fleetsim: unknown --hw {args.hw!r} (known: "
              f"{', '.join(sorted(HARDWARE_MODELS))})", file=sys.stderr)
        return 2
    ranked = fs.rank_plans_by_goodput(
        docs, policy, dists,
        hw=HARDWARE_MODELS[args.hw], flops_per_step=flops,
        rate_per_chip_per_h=args.failure_rate,
        horizon_s=args.horizon_h * 3600.0,
        seeds=tuple(range(args.seed, args.seed + args.seeds)),
    )
    print(f"Plans ranked by predicted goodput-under-failures "
          f"({args.procs} procs, {args.failure_rate:g}/chip/h, "
          f"{args.horizon_h:g}h horizon, hw {args.hw}; metric = "
          "surviving steps per capacity-second):")
    for i, row in enumerate(ranked):
        print(f"  #{i + 1} {row['plan']:<28} "
              f"{row['progress_steps_per_cap_s']:,.3f} steps/cap-s  "
              f"step {row['step_s'] * 1e3:,.3f} ms  "
              f"eff-goodput {row['effective_goodput_ratio']:.2%}  "
              f"(bytes-score {row['score']:,})"
              + ("  [ABORTED]" if row["aborted"] else ""))
        print(f"      {row['step_why']}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    mode = p.add_argument_group("modes (default: forward-simulate once)")
    mode.add_argument("--validate", metavar="RUN_DIR",
                      help="replay a supervised run's measured failure "
                      "history and assert sim-vs-ledger agreement")
    mode.add_argument("--cadence-search", action="store_true",
                      help="search checkpoint cadences, cross-checked "
                      "against the Young/Daly optimum")
    mode.add_argument("--sweep", action="append", metavar="KNOB=V1,V2",
                      help="rank a policy grid over these knob values "
                      "(repeatable; SimPolicy or SupervisorPolicy "
                      "fields)")
    mode.add_argument("--plans", nargs="+", metavar="PLAN.json",
                      help="rank autoshard plan manifests by "
                      "goodput-under-failures (cost-model step seconds)")
    pol = p.add_argument_group("policy (shared SupervisorPolicy + workload)")
    pol.add_argument("--procs", type=int, default=2)
    pol.add_argument("--min-procs", type=int, default=1)
    pol.add_argument("--max-restarts", type=int, default=3)
    pol.add_argument("--restart-backoff", type=float, default=1.0)
    pol.add_argument("--backoff-cap", type=float, default=30.0)
    pol.add_argument("--grace", type=float, default=10.0)
    pol.add_argument("--grow-after", type=float, default=0.0)
    pol.add_argument("--checkpoint-every", type=int, default=0,
                     metavar="STEPS")
    pol.add_argument("--step-time", type=float, default=None, metavar="SEC",
                     help="steady step seconds (default: the "
                     "distributions' measured mean, else 1.0)")
    pol.add_argument("--step-overhead", type=float, default=None,
                     metavar="SEC", help="per-step host overhead "
                     "(default: the distributions' derived value, else 0)")
    pol.add_argument("--tokens-per-step", type=float, default=0.0)
    pol.add_argument("--init-s", type=float, default=5.0)
    pol.add_argument("--compile-s", type=float, default=10.0)
    pol.add_argument("--checkpoint-write", type=float, default=1.0,
                     metavar="SEC")
    pol.add_argument("--restart-gap", type=float, default=10.0,
                     metavar="SEC")
    tr = p.add_argument_group("failure trace")
    tr.add_argument("--chips", type=int, default=None,
                    help="failing machines (default: --procs)")
    tr.add_argument("--failure-rate", type=float, default=0.01,
                    metavar="PER_CHIP_PER_H")
    tr.add_argument("--preempt-fraction", type=float, default=0.0)
    tr.add_argument("--horizon-h", type=float, default=24.0)
    tr.add_argument("--target-steps", type=int, default=None)
    tr.add_argument("--seed", type=int, default=0)
    tr.add_argument("--seeds", type=int, default=2,
                    help="seeds averaged in sweep/cadence/plan modes")
    io = p.add_argument_group("inputs / outputs")
    io.add_argument("--distributions", metavar="DISTS.json",
                    help="empirical distributions from tools/goodput.py "
                    "--distributions")
    io.add_argument("--record", metavar="RECORD.json",
                    help="--validate: measured fleet record override "
                    "(default RUN_DIR/run_record.json)")
    io.add_argument("--ratio-tol", type=float, default=0.1)
    io.add_argument("--share-tol", type=float, default=0.1)
    io.add_argument("--hw", default="tpu-v5e",
                    help="--plans: hardware model for step pricing")
    io.add_argument("--params", type=float, default=0.0,
                    help="--plans: parameter count for 6*P*T step flops")
    io.add_argument("--flops-per-step", type=float, default=0.0)
    io.add_argument("-o", "--json-out", metavar="OUT.json",
                    help="write the predicted record (drop fleetsim.json "
                    "into a run dir for live_top's predicted line)")
    args = p.parse_args(argv)

    try:
        if args.validate:
            return run_validate(args)
        dists = (
            fs.Distributions.load(args.distributions)
            if args.distributions else fs.Distributions()
        )
        if args.step_overhead is None:
            args.step_overhead = dists.step_overhead_s(0.0)
        if args.step_time is None:
            # the measured step-time distribution wins over the default
            args.step_time = dists.mean("steady_step", 1.0)
        policy = _build_policy(args)
        if args.cadence_search:
            return run_cadence_search(args, policy, dists)
        if args.plans:
            return run_plans(args, policy, dists)
        if args.sweep:
            grid = fs.policy_variants(policy, _parse_sweep(args.sweep))
            ranked = fs.rank_policies(
                grid, dists,
                n_chips=args.chips or args.procs,
                rate_per_chip_per_h=args.failure_rate,
                horizon_s=args.horizon_h * 3600.0,
                preempt_fraction=args.preempt_fraction,
                seeds=tuple(range(args.seed, args.seed + args.seeds)),
            )
            print(f"Policies ranked by effective goodput "
                  f"({len(ranked)} candidate(s), "
                  f"{args.seeds} seed(s) averaged):")
            for i, row in enumerate(ranked):
                print(f"  #{i + 1} {row['label']:<44} "
                      f"eff {row['effective_goodput_ratio']:.2%}  "
                      f"ledger {row['goodput_ratio']:.2%}"
                      + ("  [ABORTED]" if row["aborted"] else ""))
            _write_out(args.json_out, ranked[0]["record"])
            return 0
        trace = fs.synthesize_failure_trace(
            args.chips or args.procs,
            rate_per_chip_per_h=args.failure_rate,
            horizon_s=args.horizon_h * 3600.0,
            seed=args.seed,
            preempt_fraction=args.preempt_fraction,
        )
        rec = fs.simulate(
            policy, trace, dists,
            horizon_s=args.horizon_h * 3600.0,
            target_steps=args.target_steps, seed=args.seed,
        )
        m = rec["metrics"]
        print(render_record(
            rec, title=f"Fleetsim prediction ({args.procs} procs, "
            f"{len(trace)} failure event(s), seed {args.seed})"
        ))
        print(f"  effective goodput {m['effective_goodput_ratio']:.2%} "
              f"({m['lost_steps']} lost step(s), "
              f"{m['restarts_used']} restart(s), "
              f"{m['generations']} generation(s))"
              + (f"; ABORTED: {m['abort_reason']}"
                 if m["aborted"] else ""))
        _write_out(args.json_out, rec)
        return 0
    except (OSError, ValueError) as e:
        print(f"fleetsim: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
