#!/usr/bin/env python
"""serve_fleet: N supervised serve replicas behind the failover router.

The serving-fleet CLI (`serve/fleet.py`, docs/SERVING.md "Serving
fleet"): a `ReplicaSupervisor` spawns N independent
`python -m distributed_neural_network_tpu.serve --port 0` replicas
(stable per-rank heartbeat files advertise each ephemeral /metrics
URL), a `FleetRouter` fronts them with the same `POST /v1/generate`
surface plus least-loaded dispatch and bounded failover (a replica
dying mid-stream re-dispatches to a survivor with already-streamed
tokens suppressed - client streams stay byte-identical to the offline
oracle), and an optional autoscaler loop scales the fleet on
queue-depth and dominant-cause SLO pressure.

Replica flags (model geometry, engine knobs) follow ``--`` and are
passed through to every replica verbatim - the same flags
`tools/loadgen.py --check-oracle` needs to rebuild the oracle model.

Examples:
  # 2 replicas, router on an ephemeral port (URL printed)
  python tools/serve_fleet.py --replicas 2 --run-dir /tmp/fleet \\
      --port 0 -- --d-model 64 --n-layers 2 --max-seq-len 256

  # chaos: SIGKILL rank1 8s in (the CI failover leg)
  python tools/serve_fleet.py --replicas 2 --run-dir /tmp/fleet \\
      --chaos-kill-rank 1 --chaos-kill-after-s 8 -- --d-model 64

  # autoscale 1..3 on queue pressure + TTFT SLO
  python tools/serve_fleet.py --replicas 1 --min-replicas 1 \\
      --max-replicas 3 --autoscale --slo ttft_p99=0.5 \\
      --run-dir /tmp/fleet -- --d-model 64

SIGTERM/SIGINT stop the fleet cleanly (router closed, replicas
SIGTERMed - each drains and exits 0) and print one machine-readable
``FLEET_SUMMARY {json}`` line. Replica crashes write
``<run-dir>/postmortem.json`` exactly like training workers.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def parse_slo(spec: str) -> dict:
    """``ttft_p99=0.5,e2e_p95=2.0`` -> {key: seconds} (keys validated
    by serve/fleet.py slo_readout)."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        out[key.strip()] = float(val)
    if not out:
        raise ValueError("empty --slo spec")
    return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        argv, replica_args = argv[:split], argv[split + 1:]
    else:
        replica_args = []
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--replicas", type=int, default=2,
                   help="initial replica count (default 2)")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--run-dir", required=True,
                   help="heartbeats, logs, per-replica goodput "
                   "records, postmortem.json")
    p.add_argument("--port", type=int, default=8080,
                   help="router port; 0 = ephemeral (URL printed)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="replica failure-restart budget for the run")
    p.add_argument("--restart-backoff-s", type=float, default=0.5)
    p.add_argument("--grace-s", type=float, default=10.0,
                   help="retirement SIGTERM -> SIGKILL grace (the "
                   "drain-and-exit window)")
    p.add_argument("--poll-s", type=float, default=0.2)
    p.add_argument("--autoscale", action="store_true",
                   help="run the SLO-driven autoscaler loop "
                   "(serve/fleet.py autoscale_decision)")
    p.add_argument("--autoscale-interval-s", type=float, default=5.0)
    p.add_argument("--queue-high", type=int, default=8,
                   help="fleet queue depth that triggers scale-up")
    p.add_argument("--slo", default=None,
                   help="SLO gates for the autoscaler, e.g. "
                   "ttft_p99=0.5,e2e_p95=2.0 - queue_wait-dominant "
                   "violations scale up; kv_alloc_stall-dominant ones "
                   "hold with add-KV-capacity advice")
    p.add_argument("--scale-down-idle-s", type=float, default=60.0)
    p.add_argument("--duration-s", type=float, default=0.0,
                   help="stop after this long (0 = until SIGTERM)")
    p.add_argument("--chaos-kill-rank", type=int, default=None,
                   help="SIGKILL this replica rank once (CI chaos leg)")
    p.add_argument("--chaos-kill-after-s", type=float, default=5.0,
                   help="chaos delay, measured from the moment every "
                   "replica is up (not from process start), so the "
                   "kill lands under load regardless of warmup time")
    args = p.parse_args(argv)
    if not 1 <= args.min_replicas <= args.replicas <= args.max_replicas:
        p.error("need 1 <= --min-replicas <= --replicas <= "
                "--max-replicas")
    slo = None
    if args.slo:
        try:
            slo = parse_slo(args.slo)
        except ValueError as e:
            p.error(f"--slo: {e}")

    from distributed_neural_network_tpu.serve.fleet import (
        FleetRouter,
        autoscale_decision,
        collect_records,
        slo_readout,
    )
    from distributed_neural_network_tpu.train.supervisor import (
        ReplicaSupervisor,
        SupervisorPolicy,
    )
    from distributed_neural_network_tpu.utils.obs import MetricsRegistry

    registry = MetricsRegistry()
    command = [
        sys.executable, "-m", "distributed_neural_network_tpu.serve",
        "--port", "0", *replica_args,
    ]
    # replicas must import the package regardless of the CLI's cwd
    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = REPO + (
        os.pathsep + base_env["PYTHONPATH"]
        if base_env.get("PYTHONPATH") else ""
    )
    policy = SupervisorPolicy(
        nprocs=args.replicas,
        min_procs=args.min_replicas,
        max_restarts=args.max_restarts,
        restart_backoff_s=args.restart_backoff_s,
        grace_s=args.grace_s,
    )
    sup = ReplicaSupervisor(
        command, policy, run_dir=args.run_dir, base_env=base_env,
        registry=registry,
    ).start()
    router = FleetRouter(
        registry, watch_dir=sup.hb_dir, port=args.port, host=args.host,
    )
    router.set_target(args.replicas)
    print(
        f"fleet router on {router.url} ({args.replicas} replica(s), "
        f"autoscale {'on' if args.autoscale else 'off'} "
        f"[{args.min_replicas}..{args.max_replicas}]; endpoints: "
        "POST /v1/generate, GET /v1/status, GET /v1/fleet, "
        "POST /v1/drain, /metrics)",
        flush=True,
    )

    stop = threading.Event()

    def _stop(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)

    t_start = time.monotonic()
    t_autoscale = t_start
    t_last_busy = t_start
    t_all_up = None
    chaos_done = args.chaos_kill_rank is None
    while not stop.wait(args.poll_s):
        sup.tick()
        now = time.monotonic()
        if args.duration_s > 0 and now - t_start >= args.duration_s:
            break
        if t_all_up is None and sum(
            1 for r in router.replicas() if r.state == "up"
        ) >= sup.target:
            t_all_up = now
        if not chaos_done and t_all_up is not None \
                and now - t_all_up >= args.chaos_kill_after_s:
            # hold fire until the victim is actually serving router
            # traffic, so the SIGKILL lands mid-stream and the
            # failover path (not just respawn) is exercised
            victim = f"rank{args.chaos_kill_rank}"
            serving = any(
                r.replica_id == victim and (r.inflight or r.active)
                for r in router.replicas()
            )
            w = sup.workers.get(args.chaos_kill_rank)
            if w is None or not w.alive():
                chaos_done = True
            elif serving:
                chaos_done = True
                print(
                    f"(fleet chaos: SIGKILL rank{args.chaos_kill_rank} "
                    f"pid {w.proc.pid})",
                    flush=True,
                )
                w.kill(signal.SIGKILL)
        reps = router.replicas()
        busy = any(
            r.queue_depth or r.active or r.inflight for r in reps
        )
        if busy:
            t_last_busy = now
        if args.autoscale and now - t_autoscale \
                >= args.autoscale_interval_s:
            t_autoscale = now
            gates = None
            if slo:
                records = collect_records(
                    r.url for r in reps if r.state == "up"
                )
                if records:
                    gates = slo_readout(records, slo)
            decision = autoscale_decision(
                actual=sup.target,
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                queue_depth=sum(r.queue_depth for r in reps),
                queue_high=args.queue_high,
                gates=gates,
                idle_s=now - t_last_busy,
                scale_down_idle_s=args.scale_down_idle_s,
            )
            if decision["action"] != "hold":
                print(
                    f"(fleet autoscale: {decision['action']} -> "
                    f"{decision['target']} - {decision['reason']})",
                    flush=True,
                )
                sup.scale_to(
                    decision["target"], drain=router.drain_replica
                )
            router.set_target(decision["target"])

    router.close()
    sup_summary = sup.stop()
    print("FLEET_SUMMARY " + json.dumps({
        "router_url": router.url,
        "requests_completed": int(
            registry.counter("fleet_router_requests_total")
            .labels(status="completed").value
        ),
        "router_retries": int(
            registry.counter("fleet_router_retries_total").value
        ),
        "replica_failures_observed": int(
            registry.counter("fleet_replica_failures_total").value
        ),
        "target_replicas": sup.target,
        "supervisor": sup_summary,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
