#!/usr/bin/env python
"""Summarize a Chrome trace-event JSON (and optional metrics JSONL pair).

Reads a trace written by `--trace-out` (utils/tracing.py Tracer.export) -
or any Chrome trace-event file - and prints a phase breakdown table
(count, total, p50/p95/max per span name), the step-level statistics
(compile vs steady-state step time, throughput, comm bytes/step, device
memory, MFU or an explicit "unavailable" reason), and, when a metrics
JSONL file is also given, the `step/*` series it carries.

Strictness: the file must be STRICT JSON - a bare NaN/Infinity token
(which `json.dumps` emits by default and utils/metrics.py/tracing.py are
careful never to write) is rejected with a clear error instead of being
silently accepted. Stdlib-only: no jax, no repo imports - runs anywhere.

Usage:
  python tools/trace_summary.py trace.json [metrics.jsonl]
  python tools/trace_summary.py trace.json --lint lm_zero_overlap
  python tools/trace_summary.py --diff end.json overlap.json
  python tools/trace_summary.py merged.json --rank 1   # one rank of a
                                                       # trace_merge doc
  python tools/trace_summary.py merged.json --goodput  # wall-clock
                                                       # taxonomy view

Multi-rank traces (per-rank shards merged by `tools/trace_merge.py`, or
any rank-stamped trace) are detected from their ``rank{N}`` process
metadata: the default report aggregates every rank WITH AN EXPLICIT NOTE
(it used to mix ranks' spans silently), and ``--rank N`` restricts the
phase table / step stats to one rank - including that rank's own
``stepStats`` embed from the merged document's ``rankStepStats``.

--diff A B prints the side-by-side phase breakdown and StepStats delta
between two traces - the manual compare-two-runs-by-eye workflow (e.g.
``--grad-sync end`` vs ``overlap``) as one table: per-phase count/total/
p50 for both files with the total delta, then the steady-state step
time, throughput, compile time, and collective-bytes deltas from the
two stepStats embeds.

--lint CONFIG additionally compares the trace's measured per-step
collective bytes (the stepStats embed's ``comm_bytes_per_step`` ring
estimate, and the ``grad_bucket`` plan events when present) against the
shardlint manifest's static payload for that config
(distributed_neural_network_tpu/analysis/manifests/CONFIG.json) and
prints the delta. The two use different conventions - the manifest counts
logical payload bytes per collective, the runtime estimate counts ring
all-reduce wire bytes (~2(n-1)/n of the tree) - so the printed ratio is
the cross-check, not an equality; ``--lint-tolerance PCT`` turns a
larger-than-PCT ratio drift into a non-zero exit for CI use.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from collections import defaultdict

# phase rows print in this order when present; anything else follows by
# descending total time (mirrors utils/timers.py CANONICAL_PHASES plus the
# tracer's own span names)
PREFERRED_ORDER = (
    "data_loading",
    "train_step",
    "train_span",
    "train_epoch",
    "training",
    "sync",
    "communication",
    "eval",
    "evaluation",
)


def _reject_constant(name: str):
    raise ValueError(
        f"non-strict JSON token {name!r} (bare NaN/Infinity); the writer "
        "must serialize non-finite floats as null"
    )


def strict_loads(text: str):
    return json.loads(text, parse_constant=_reject_constant)


def percentile(xs, p: float) -> float:
    ys = sorted(xs)
    k = max(0, min(len(ys) - 1, int(math.ceil(p / 100.0 * len(ys))) - 1))
    return ys[k]


def load_trace(path: str) -> dict:
    with open(path) as f:
        doc = strict_loads(f.read())
    if isinstance(doc, list):  # the bare-array Chrome trace variant
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace-event document")
    return doc


def trace_ranks(doc: dict) -> dict:
    """{rank: pid} from ``rank{N}`` process_name metadata - present in
    rank-stamped shards (`utils/tracing.py set_process`) and merged
    timelines (`tools/trace_merge.py`, where pid == rank). Empty for
    plain single-process traces."""
    out: dict[int, int] = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            m = re.match(
                r"rank(\d+)\b", str((ev.get("args") or {}).get("name", ""))
            )
            if m:
                out[int(m.group(1))] = ev.get("pid")
    return out


def filter_rank(doc: dict, rank: int) -> dict:
    """A view of ``doc`` restricted to one rank's events (by pid).

    Raises ValueError naming the available ranks when ``rank`` is not in
    the trace - silently returning an empty table would look like a run
    with no spans. The rank's own stepStats embed (merged docs carry
    them under ``rankStepStats``) is promoted to the top level.
    """
    ranks = trace_ranks(doc)
    if rank not in ranks:
        raise ValueError(
            f"rank {rank} not in trace (ranks: "
            f"{sorted(ranks) if ranks else 'none - not a rank-stamped trace'})"
        )
    pid = ranks[rank]
    out = dict(doc)
    out["traceEvents"] = [
        ev for ev in doc.get("traceEvents", []) if ev.get("pid") == pid
    ]
    per_rank = (doc.get("rankStepStats") or {}).get(str(rank))
    if isinstance(per_rank, dict):
        out["stepStats"] = per_rank
    elif len(ranks) > 1:
        # a multi-rank doc's top-level embed (if any) is not THIS rank's
        out.pop("stepStats", None)
    return out


def phase_table(events) -> str:
    spans = defaultdict(list)
    for ev in events:
        if ev.get("ph") == "X" and "dur" in ev:
            spans[ev.get("name", "?")].append(float(ev["dur"]) / 1e6)
    if not spans:
        return "(no complete spans in trace)"
    names = [n for n in PREFERRED_ORDER if n in spans]
    names += sorted(
        (n for n in spans if n not in PREFERRED_ORDER),
        key=lambda n: -sum(spans[n]),
    )
    w = max(12, max(len(n) for n in names))
    head = (
        f"{'phase':<{w}}  {'count':>5}  {'total_s':>9}  "
        f"{'p50_ms':>9}  {'p95_ms':>9}  {'max_ms':>9}"
    )
    lines = [head, "-" * len(head)]
    for n in names:
        xs = spans[n]
        lines.append(
            f"{n:<{w}}  {len(xs):>5}  {sum(xs):>9.3f}  "
            f"{percentile(xs, 50) * 1e3:>9.2f}  "
            f"{percentile(xs, 95) * 1e3:>9.2f}  {max(xs) * 1e3:>9.2f}"
        )
    return "\n".join(lines)


def _phase_spans(events) -> dict:
    """{span name: [durations_s]} of the complete (X) events."""
    spans = defaultdict(list)
    for ev in events:
        if ev.get("ph") == "X" and "dur" in ev:
            spans[ev.get("name", "?")].append(float(ev["dur"]) / 1e6)
    return spans


def _ordered_names(*span_dicts):
    present = set()
    for d in span_dicts:
        present.update(d)
    names = [n for n in PREFERRED_ORDER if n in present]
    names += sorted(
        (n for n in present if n not in PREFERRED_ORDER),
        key=lambda n: -max(sum(d.get(n, [])) for d in span_dicts),
    )
    return names


def _pct_delta(a, b) -> str:
    if not (
        isinstance(a, (int, float)) and isinstance(b, (int, float)) and a
    ):
        return ""
    return f"{(b - a) / a * 100.0:+.1f}%"


def diff_report(doc_a: dict, doc_b: dict, name_a: str, name_b: str) -> str:
    """Side-by-side phase + StepStats comparison of two traces."""
    spans_a = _phase_spans(doc_a.get("traceEvents", []))
    spans_b = _phase_spans(doc_b.get("traceEvents", []))
    lines = [f"Trace diff: A = {name_a}   B = {name_b}", ""]
    names = _ordered_names(spans_a, spans_b)
    if names:
        w = max(12, max(len(n) for n in names))
        head = (
            f"{'phase':<{w}}  {'cnt A':>6} {'cnt B':>6}  "
            f"{'total_s A':>10} {'total_s B':>10} {'d_total':>8}  "
            f"{'p50_ms A':>9} {'p50_ms B':>9}"
        )
        lines += [head, "-" * len(head)]
        for n in names:
            xa, xb = spans_a.get(n, []), spans_b.get(n, [])
            ta, tb = sum(xa), sum(xb)
            lines.append(
                f"{n:<{w}}  {len(xa):>6} {len(xb):>6}  "
                f"{ta:>10.3f} {tb:>10.3f} {_pct_delta(ta, tb):>8}  "
                + (f"{percentile(xa, 50) * 1e3:>9.2f}" if xa
                   else f"{'-':>9}")
                + " "
                + (f"{percentile(xb, 50) * 1e3:>9.2f}" if xb
                   else f"{'-':>9}")
            )
    else:
        lines.append("(no complete spans in either trace)")
    sa = doc_a.get("stepStats") or step_stats_from_spans(
        doc_a.get("traceEvents", [])
    ) or {}
    sb = doc_b.get("stepStats") or step_stats_from_spans(
        doc_b.get("traceEvents", [])
    ) or {}
    rows = [
        ("steps", "steps", "{:d}"),
        ("compile_s", "compile", "{:.4f} s"),
        ("steady_mean_s", "steady mean", "{:.4f} s"),
        ("steady_p50_s", "steady p50", "{:.4f} s"),
        ("steady_p95_s", "steady p95", "{:.4f} s"),
        ("throughput_items_per_s", "throughput", "{:,.1f}/s"),
        ("comm_bytes_per_step", "comm bytes/step", "{:,d} B"),
        ("mfu_pct", "MFU", "{:.2f} %"),
    ]
    stat_lines = []
    for key, label, fmt in rows:
        va, vb = sa.get(key), sb.get(key)
        if va is None and vb is None:
            continue

        def f(v):
            if v is None:
                return "n/a"
            try:
                return fmt.format(int(v) if "d}" in fmt else v)
            except (ValueError, TypeError):
                return str(v)

        stat_lines.append(
            f"  {label:<16} A: {f(va):>14}   B: {f(vb):>14}   "
            f"{_pct_delta(va, vb)}"
        )
    if stat_lines:
        lines += ["", "Step stats delta (B vs A):", *stat_lines]
    else:
        lines += ["", "Step stats delta: unavailable (no stepStats embed "
                  "or train_step spans in either trace)"]
    return "\n".join(lines)


def step_stats_from_spans(events) -> dict | None:
    """Fallback aggregation straight from train_step spans (traces written
    by other tools, or runs without the StepStats embed)."""
    recs = []
    for ev in events:
        if ev.get("ph") == "X" and ev.get("name") in ("train_step", "train_span"):
            args = ev.get("args") or {}
            recs.append(
                {
                    "wall_s": float(ev.get("dur", 0.0)) / 1e6,
                    "step": args.get("step", args.get("epoch0", len(recs))),
                    "items": float(args.get("items", 0.0) or 0.0),
                }
            )
    if not recs:
        return None
    recs.sort(key=lambda r: r["step"])
    steady = recs[1:] or recs
    walls = [r["wall_s"] for r in steady]
    total = sum(walls)
    items = sum(r["items"] for r in steady)
    return {
        "steps": len(recs),
        "compile_steps": 1,
        "compile_s": recs[0]["wall_s"],
        "steady_steps": len(steady),
        "steady_includes_compile": steady is recs,
        "steady_mean_s": total / len(walls),
        "steady_p50_s": percentile(walls, 50),
        "steady_p95_s": percentile(walls, 95),
        "steady_total_s": total,
        "throughput_items_per_s": items / total if total > 0 and items else None,
        "item_label": "items",
        "n_devices": None,
        "comm_bytes_per_step": None,
        "device_memory_peak_bytes": None,
        "mfu_pct": None,
        "mfu_note": "unavailable: trace carries no stepStats embed "
        "(FLOPs/peak unknown)",
        "flops_source": None,
    }


def fmt_step_stats(s: dict, source: str) -> str:
    lines = [f"Step stats ({source}):"]
    lines.append(
        f"  steps: {s.get('steps')} "
        f"({s.get('compile_steps')} compile + {s.get('steady_steps')} steady"
        + (", single-dispatch: steady includes compile)"
           if s.get("steady_includes_compile") else ")")
    )
    if s.get("compile_s") is not None:
        lines.append(f"  compile step: {s['compile_s']:.4f} s")
    if s.get("steady_mean_s") is not None:
        lines.append(
            f"  steady-state step time: mean {s['steady_mean_s']:.4f} s, "
            f"p50 {s['steady_p50_s']:.4f} s, p95 {s['steady_p95_s']:.4f} s"
        )
    else:
        lines.append("  steady-state step time: unavailable (no steps)")
    thr = s.get("throughput_items_per_s")
    label = s.get("item_label") or "items"
    lines.append(
        "  steady-state throughput: "
        + (f"{thr:,.1f} {label}/s" if thr else "unavailable")
    )
    if s.get("comm_bytes_per_step") is not None:
        lines.append(
            f"  collective payload: {s['comm_bytes_per_step']:,} bytes/step"
        )
    mem = s.get("device_memory_peak_bytes")
    if mem:
        lines.append(
            "  device memory peak: "
            + ", ".join(f"{k}={v:,} B" for k, v in sorted(mem.items()))
        )
    anom = s.get("anomalies")
    if anom:
        lines.append(
            "  guard anomalies: "
            + ", ".join(f"{k}={v}" for k, v in sorted(anom.items()))
        )
    if s.get("mfu_pct") is not None:
        lines.append(
            f"  est. MFU: {s['mfu_pct']:.2f}% "
            f"(FLOPs source: {s.get('flops_source')})"
        )
    else:
        lines.append(
            f"  est. MFU: {s.get('mfu_note') or 'unavailable'}"
        )
    return "\n".join(lines)


def guard_events_table(events) -> str | None:
    """One line per guard action with counts, from the `guard` instant
    events the policy loop emits (train/guard.py; docs/ROBUSTNESS.md) -
    None when the trace carries none."""
    by_action = defaultdict(int)
    by_kind = defaultdict(int)
    for ev in events:
        if ev.get("name") != "guard" or ev.get("ph") != "i":
            continue
        args = ev.get("args") or {}
        by_action[str(args.get("action", "?"))] += 1
        by_kind[str(args.get("kind", "?"))] += 1
    if not by_action:
        return None
    return (
        "Guard events: "
        + ", ".join(f"{k}={v}" for k, v in sorted(by_action.items()))
        + "  (kinds: "
        + ", ".join(f"{k}={v}" for k, v in sorted(by_kind.items()))
        + ")"
    )


def jsonl_step_series(path: str) -> str:
    series = defaultdict(list)
    bad = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = strict_loads(line)
            except ValueError:
                bad += 1
                continue
            if not isinstance(ev, dict):
                bad += 1
                continue
            if "value" in ev and isinstance(ev.get("series"), str):
                v = ev["value"]
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    series[ev["series"]].append(float(v))
    if bad:
        print(
            f"({bad} malformed JSONL line(s) skipped in {path})",
            file=sys.stderr,
        )
    steps = {k: v for k, v in series.items() if k.startswith("step/")}
    if not steps:
        return f"(no step/* series in {path})"
    lines = [f"Metrics step series ({path}):"]
    for k in sorted(steps):
        xs = steps[k]
        lines.append(
            f"  {k}: n={len(xs)} last={xs[-1]:.6g} "
            f"p50={percentile(xs, 50):.6g} p95={percentile(xs, 95):.6g}"
        )
    return "\n".join(lines)


def default_manifest_dir() -> str:
    """The in-repo shardlint manifest directory, resolved relative to this
    script (stdlib-only - no repo import)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(
        os.path.dirname(here),
        "distributed_neural_network_tpu", "analysis", "manifests",
    )


def measured_collective_bytes(doc: dict):
    """(comm_bytes_per_step, grad_bucket summary dict | None) from a trace.

    comm_bytes_per_step is the stepStats embed's runtime ring estimate;
    the grad_bucket instant events (one per bucket of the overlap plan)
    give per-bucket payloads and the per-step total they imply.
    """
    stats = doc.get("stepStats") or {}
    comm = stats.get("comm_bytes_per_step")
    buckets = []
    accum = 1
    for ev in doc.get("traceEvents", []):
        if ev.get("name") == "grad_bucket" and ev.get("ph") == "i":
            a = ev.get("args") or {}
            if isinstance(a.get("bytes"), (int, float)):
                buckets.append(int(a["bytes"]))
                accum = max(accum, int(a.get("per_microbatch", 1) or 1))
    bucket_summary = None
    if buckets:
        bucket_summary = {
            "count": len(buckets),
            "bytes_per_microbatch": sum(buckets),
            "bytes_per_step": sum(buckets) * accum,
            "accum_steps": accum,
        }
    return comm, bucket_summary


def lint_against_manifest(
    doc: dict, config: str, manifest_dir: str | None = None,
    tolerance_pct: float | None = None,
):
    """(report lines, ok) - measured trace bytes vs the shardlint manifest."""
    path = os.path.join(
        manifest_dir or default_manifest_dir(), f"{config}.json"
    )
    if not os.path.exists(path):
        return [
            f"lint: no shardlint manifest for config {config!r} at {path} "
            "- generate with: python tools/shardlint.py --config "
            f"{config} --write-manifest"
        ], False
    with open(path) as f:
        man = strict_loads(f.read())
    static = man.get("total_collective_bytes")
    comm, buckets = measured_collective_bytes(doc)
    lines = [f"Shardlint manifest lint (config {config!r}):"]
    lines.append(
        f"  manifest static payload: "
        + (f"{static:,} B/step" if isinstance(static, int) else "n/a")
        + f" (jax {man.get('jax_version')}, {man.get('trace_mode')} trace, "
        f"mesh {man.get('mesh')})"
    )
    if comm is not None:
        lines.append(
            f"  trace comm_bytes_per_step: {comm:,} B/step "
            "(runtime ring all-reduce estimate)"
        )
    if buckets:
        lines.append(
            f"  grad_bucket events: {buckets['count']} bucket(s), "
            f"{buckets['bytes_per_microbatch']:,} B/microbatch -> "
            f"{buckets['bytes_per_step']:,} B/step at "
            f"accum={buckets['accum_steps']}"
        )
    measured = comm if comm is not None else (
        buckets["bytes_per_step"] if buckets else None
    )
    if measured is None:
        lines.append(
            "  lint: trace carries no stepStats comm_bytes_per_step and no "
            "grad_bucket events - nothing to compare"
        )
        return lines, tolerance_pct is None
    if not isinstance(static, int) or static <= 0:
        if measured == 0 and (static in (0, None)):
            lines.append("  delta: both zero (single-device step)")
            return lines, True
        lines.append(
            f"  lint: manifest static payload is {static!r} but the trace "
            f"measured {measured:,} B/step"
        )
        return lines, False
    delta = measured - static
    ratio = measured / static
    lines.append(
        f"  delta (trace - manifest): {delta:+,} B/step "
        f"(ratio {ratio:.3f}; conventions differ - see --help)"
    )
    ok = True
    if tolerance_pct is not None:
        drift = abs(ratio - 1.0) * 100.0
        ok = drift <= tolerance_pct
        lines.append(
            f"  tolerance: {drift:.1f}% drift vs allowed "
            f"{tolerance_pct:.1f}% -> {'OK' if ok else 'FAIL'}"
        )
    return lines, ok


# ------------------------------------------------------------- goodput view

# span -> taxonomy cause (None = train_step: first span per rank is
# compile, the rest steady_step). Mirrors utils/goodput.py's trace
# derivation - this module stays repo-import-free by design (like
# tools/live_top.py's prometheus parser), and tests cross-check the two
# implementations against each other AND against the ledger record.
GOODPUT_SPAN_CAUSE = {
    "train_step": None,
    "straggler": "stall",
    "reshard": "reshard",
    "data_loading": "data_wait",
    "checkpoint_save": "checkpoint_save",
}
GOODPUT_CAUSES = (
    "init", "compile", "steady_step", "data_wait", "checkpoint_save",
    "reshard", "rollback_recompute", "stall", "restart_gap", "idle_other",
)
# overlap priority (lower wins): instrumented spans beat the coarse
# stall window; the residual is idle_other
_GOODPUT_PRIO = {c: 0 for c in GOODPUT_CAUSES}
_GOODPUT_PRIO["stall"] = 1
_GOODPUT_PRIO["restart_gap"] = 1


def _goodput_sweep(intervals, end: float) -> dict:
    """Attribute [0, end] over (t0, t1, cause) intervals, each second
    exactly once (priority, then earliest interval wins overlaps)."""
    import heapq

    out = {c: 0.0 for c in GOODPUT_CAUSES}
    ivs = sorted(
        (max(t0, 0.0), min(t1, end), cause, seq)
        for seq, (t0, t1, cause) in enumerate(intervals)
        if t1 > 0.0 and t0 < end and t1 > t0
    )
    heap: list = []
    t, i, n = 0.0, 0, len(ivs)
    while t < end:
        while i < n and ivs[i][0] <= t:
            t0, t1, cause, seq = ivs[i]
            if t1 > t:
                heapq.heappush(
                    heap, (_GOODPUT_PRIO.get(cause, 0), t0, seq, t1, cause)
                )
            i += 1
        while heap and heap[0][3] <= t:
            heapq.heappop(heap)
        nxt = ivs[i][0] if i < n else end
        if heap:
            seg = min(heap[0][3], nxt, end)
            out[heap[0][4]] += seg - t
        else:
            seg = min(nxt, end)
            out["idle_other"] += seg - t
        t = seg
    return out


def goodput_from_trace(doc: dict) -> dict:
    """The taxonomy breakdown derived from the trace's spans alone (per
    rank/pid, aggregated in capacity-seconds); same shape as
    utils/goodput.py breakdown_from_trace."""
    per_pid: dict = {}
    for ev in doc.get("traceEvents", ()):
        if ev.get("ph") != "X" or ev.get("name") not in GOODPUT_SPAN_CAUSE:
            continue
        t0 = float(ev.get("ts", 0.0)) / 1e6
        t1 = t0 + float(ev.get("dur") or 0.0) / 1e6
        per_pid.setdefault(ev.get("pid", 0), []).append(
            (t0, t1, ev.get("name"))
        )
    buckets = {c: 0.0 for c in GOODPUT_CAUSES}
    wall = 0.0
    per_rank = {}
    for pid, spans in sorted(per_pid.items()):
        spans.sort()
        intervals = []
        first = True
        first_t0 = None
        for t0, t1, name in spans:
            cause = GOODPUT_SPAN_CAUSE[name]
            if cause is None:
                cause = "compile" if first else "steady_step"
                if first:
                    first_t0 = t0
                first = False
            intervals.append((t0, t1, cause))
        if first_t0 is not None and first_t0 > 0:
            intervals.append((0.0, first_t0, "init"))
        end = max(t1 for _, t1, _ in intervals)
        b = _goodput_sweep(intervals, end)
        per_rank[pid] = {
            "wall_s": round(end, 6),
            "goodput_ratio": round(b["steady_step"] / end, 6)
            if end > 0 else None,
            "buckets": {c: round(v, 6) for c, v in b.items()},
        }
        for c, v in b.items():
            buckets[c] += v
        wall += end
    return {
        "kind": "trace",
        "wall_s": round(wall, 6),
        "goodput_s": round(buckets["steady_step"], 6),
        "goodput_ratio": round(buckets["steady_step"] / wall, 6)
        if wall > 0 else None,
        "badput_s": {c: round(v, 6) for c, v in buckets.items()
                     if c != "steady_step"},
        "per_rank": per_rank,
    }


def goodput_report(doc: dict) -> str:
    """The --goodput section: span-derived breakdown table, plus the
    cross-check against the ledger's embedded record when the trace
    carries one (`utils/tracing.py export(goodput=...)`)."""
    derived = goodput_from_trace(doc)
    total = derived["wall_s"]
    if total <= 0:
        return "Goodput: unavailable (no attributable spans in trace)"
    lines = ["Goodput (derived from trace spans):"]
    ratio = derived["goodput_ratio"]
    lines.append(
        f"  goodput {100.0 * ratio:.2f}% of {total:.2f}s"
        + (f" across {len(derived['per_rank'])} rank(s)"
           if len(derived["per_rank"]) > 1 else "")
    )
    lines.append(f"  {'cause':<20} {'seconds':>12} {'share':>8}")
    causes = dict(derived["badput_s"])
    causes["steady_step"] = derived["goodput_s"]
    for c in GOODPUT_CAUSES:
        v = causes.get(c, 0.0)
        if v <= 0 and c not in ("steady_step", "idle_other"):
            continue
        tag = "  <- goodput" if c == "steady_step" else ""
        lines.append(f"  {c:<20} {v:>12.3f} {v / total:>7.2%}{tag}")
    embed = doc.get("goodput")
    if isinstance(embed, dict) and embed.get("goodput_ratio") is not None:
        er = float(embed["goodput_ratio"])
        lines.append(
            f"  ledger record embed: goodput {100.0 * er:.2f}% over "
            f"{embed.get('wall_s', 0.0):.2f}s "
            f"(delta vs span-derived {100.0 * (ratio - er):+.2f} pp; the "
            "record also counts pre-tracer init and untraced host time)"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "trace", nargs="?", default=None,
        help="Chrome trace-event JSON (--trace-out)",
    )
    ap.add_argument(
        "jsonl", nargs="?", default=None,
        help="optional metrics JSONL pair (--metrics-jsonl)",
    )
    ap.add_argument(
        "--diff", nargs=2, metavar=("A.json", "B.json"), default=None,
        help="compare two traces: side-by-side phase breakdown and "
        "StepStats delta (B vs A)",
    )
    ap.add_argument(
        "--rank", type=int, default=None, metavar="N",
        help="restrict a rank-stamped or merged multi-rank trace "
        "(tools/trace_merge.py) to rank N's events before reporting; "
        "default aggregates every rank (noted when the trace is "
        "multi-rank). Applies to --diff's two traces as well",
    )
    ap.add_argument(
        "--goodput", action="store_true",
        help="append the wall-clock goodput/badput taxonomy breakdown "
        "derived from the trace's spans (train_step/straggler/reshard/"
        "data_loading), cross-checked against the ledger record the "
        "trace embeds when present (docs/OBSERVABILITY.md 'Goodput "
        "accounting'; tools/goodput.py renders run records directly)",
    )
    ap.add_argument(
        "--lint", metavar="CONFIG", default=None,
        help="compare measured collective bytes against the shardlint "
        "manifest for CONFIG and print the delta",
    )
    ap.add_argument(
        "--manifest-dir", default=None,
        help="shardlint manifest directory (default: the in-repo one)",
    )
    ap.add_argument(
        "--lint-tolerance", type=float, default=None, metavar="PCT",
        help="with --lint: exit non-zero when the measured/static ratio "
        "drifts more than PCT percent from 1.0",
    )
    args = ap.parse_args(argv)

    def apply_rank(doc, name):
        """--rank filter / multi-rank aggregation note for one trace."""
        ranks = trace_ranks(doc)
        if args.rank is not None:
            label = f" [rank {args.rank}]"
            return filter_rank(doc, args.rank), name + label
        if len(ranks) > 1:
            print(
                f"({name}: merged multi-rank trace, ranks "
                f"{sorted(ranks)} - tables aggregate ALL ranks; "
                "--rank N filters to one)"
            )
        return doc, name

    if args.diff is not None:
        path_a, path_b = args.diff
        try:
            doc_a, doc_b = load_trace(path_a), load_trace(path_b)
            doc_a, path_a = apply_rank(doc_a, path_a)
            doc_b, path_b = apply_rank(doc_b, path_b)
        except (ValueError, OSError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        print(diff_report(doc_a, doc_b, path_a, path_b))
        return 0
    if args.trace is None:
        ap.error("a trace file is required (or use --diff A.json B.json)")

    try:
        doc = load_trace(args.trace)
        doc, _ = apply_rank(doc, args.trace)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    events = doc["traceEvents"]
    n_tracks = len({(e.get("pid"), e.get("tid")) for e in events})
    span_ts = [
        float(e["ts"]) for e in events if e.get("ph") == "X" and "ts" in e
    ]
    extent = (
        (max(
            float(e["ts"]) + float(e.get("dur", 0.0))
            for e in events if e.get("ph") == "X"
        ) - min(span_ts)) / 1e6
        if span_ts else 0.0
    )
    print(
        f"Trace: {args.trace} ({len(events)} events, {n_tracks} tracks, "
        f"{extent:.3f} s span)"
    )
    print()
    print(phase_table(events))
    guard_line = guard_events_table(events)
    if guard_line:
        print()
        print(guard_line)
    print()
    stats = doc.get("stepStats")
    if isinstance(stats, dict) and stats:
        print(fmt_step_stats(stats, "trace metadata"))
    else:
        derived = step_stats_from_spans(events)
        if derived is not None:
            print(fmt_step_stats(derived, "derived from train_step spans"))
        else:
            print("Step stats: unavailable (no train_step spans, no embed)")
    if args.goodput:
        print()
        print(goodput_report(doc))
    if args.jsonl:
        print()
        print(jsonl_step_series(args.jsonl))
    if args.lint:
        print()
        lines, ok = lint_against_manifest(
            doc, args.lint, args.manifest_dir, args.lint_tolerance
        )
        print("\n".join(lines))
        if not ok:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
