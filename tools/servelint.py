#!/usr/bin/env python
"""servelint CLI: static audit + roofline pricing of the serve buckets.

Enumerates the full bucket grid the serving engine's ``warmup()``
compiles (decode / chunked-prefill / spec draft+verify families x pow2
batch x pow2 table width, per precision mode), abstractly traces every
jitted program (no execution, no TPU;
distributed_neural_network_tpu/analysis/serve_trace.py) and

- lints the donation contract (KV pools + int8 scales donated, params
  never), dtype upcasts, and the quantized-dtype declaration,
- prices each bucket on the HardwareModel roofline (static tokens/s,
  prefill TTFT, KV-capacity curves - the capacity planner),
- writes or checks the per-config serve manifests
  (distributed_neural_network_tpu/analysis/manifests/serve_*.json),
  including the bucket-grid budget: an accidental new bucket dimension
  fails --check with the grid diff named.

Usage:
  python tools/servelint.py --list
  python tools/servelint.py --all --check           # the CI gate
  python tools/servelint.py --config serve_int8_kv --explain
  python tools/servelint.py --all --write-manifest  # after an
                                                    # intentional change
  python tools/servelint.py --all --check --probe extra-bucket
                                                    # the CI probe leg:
                                                    # must exit 1
  python tools/servelint.py --validate              # static tokens/s vs
                                                    # a measured serve
                                                    # bench row

Exit codes: 0 conforming; 1 lint errors, manifest mismatch, or a failed
--validate gate; 2 a config could not be built/traced or an unknown
--config name (the known list is printed). See docs/STATIC_ANALYSIS.md
"Serve lint".
"""

import argparse
import os
import sys


def _force_cpu_mesh():
    """8 virtual CPU devices, set BEFORE jax import (the repo-standard
    test mesh - tests/conftest.py does the same for pytest)."""
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "jax" in sys.modules:
        import jax

        try:  # re-assert against site hooks that pre-import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--config", action="append", default=[],
        help="serve config name(s): repeatable and/or comma-separated "
        "(--config a,b); see --list",
    )
    ap.add_argument(
        "--all", action="store_true", help="every canonical serve config"
    )
    ap.add_argument(
        "--list", action="store_true", help="list serve configs and exit"
    )
    ap.add_argument(
        "--write-manifest", action="store_true",
        help="regenerate the serve manifest(s)",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="diff fresh traces against the checked-in serve manifest(s) "
        "- grid budget, per-bucket flops/bytes/traffic, upcasts, "
        "donation",
    )
    ap.add_argument(
        "--explain", action="store_true",
        help="per-bucket table (flops, HBM bytes, gather/scatter counts, "
        "roofline tick) under each config line",
    )
    ap.add_argument(
        "--probe", choices=("extra-bucket", "drop-donation", "upcast"),
        default=None,
        help="inject a known defect before tracing (acceptance probes: "
        "each must fail --check with the bucket named)",
    )
    ap.add_argument(
        "--hw", default="cpu-host",
        help="hardware model for roofline pricing (tpu-v5e, tpu-v4, "
        "cpu-host)",
    )
    ap.add_argument(
        "--validate", action="store_true",
        help="gate the static tokens/s prediction against a measured "
        "measure_serving bench row (runs an in-process open-loop bench "
        "at reduced geometry, ~1 min) within the documented tolerance",
    )
    ap.add_argument(
        "--manifest-dir", default=None,
        help="manifest directory (default: the in-package "
        "analysis/manifests)",
    )
    ap.add_argument(
        "-q", "--quiet", action="store_true",
        help="findings and verdicts only",
    )
    args = ap.parse_args(argv)

    _force_cpu_mesh()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from distributed_neural_network_tpu.analysis import serve_trace

    if args.list:
        for name in serve_trace.serve_config_names():
            print(name)
        return 0
    if args.write_manifest and args.check:
        ap.error("--write-manifest and --check are mutually exclusive")
    if args.validate:
        rc, report = serve_trace.run_validate(hw=args.hw)
        print(report)
        return rc
    requested = [n for entry in args.config for n in entry.split(",") if n]
    known = serve_trace.serve_config_names()
    unknown = [n for n in requested if n not in known]
    if unknown:
        print(
            f"unknown serve config(s): {', '.join(unknown)}\n"
            f"known configs: {', '.join(known)}"
        )
        return 2
    names = known if args.all or not requested else requested
    mode = (
        "write" if args.write_manifest else "check" if args.check else "lint"
    )
    rc, report = serve_trace.run_servelint(
        names, mode=mode, manifest_dir=args.manifest_dir,
        verbose=not args.quiet, explain=args.explain, probe=args.probe,
        hw=args.hw,
    )
    print(report)
    if args.explain:
        print(
            "note: roofline figures above are STATIC floors "
            "(static_only: true - no queueing); for replica counts "
            "under dynamic load, run the serve twin: "
            "tools/fleetsim.py --serve --manifest "
            "distributed_neural_network_tpu/analysis/manifests/"
            "serve_<config>.json --replicas-for RATE,ttft_p99=X"
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
