#!/usr/bin/env python
"""Render, diff, and gate goodput run records (utils/goodput.py).

The training entry points (and the elastic supervisor, fleet-wide) emit
schema-versioned run records: total wall-clock partitioned into goodput
(steady training steps) and a closed badput taxonomy (init, compile,
data_wait, checkpoint_save, reshard, rollback_recompute, stall,
restart_gap, idle_other). This tool is the operator/CI surface:

  # render the breakdown table (run record, fleet record, or a Chrome
  # trace - merged traces work; trace input derives the same taxonomy
  # from the spans alone)
  python tools/goodput.py run_record.json
  python tools/goodput.py merged_trace.json

  # side-by-side share comparison of two runs
  python tools/goodput.py --diff before.json after.json

  # a run that crashed before the supervisor aggregated: point at the
  # run dir (or its records/ directory) and the per-worker
  # gen{g}_rank{r}.json write-through records aggregate on the fly
  python tools/goodput.py svrun/records

  # CI regression gate against a checked-in baseline (shardlint-style
  # exit codes: 0 = within tolerances, 1 = regression, 2 = usage/input
  # error). Tolerances are SHARES of wall-clock, so runs of different
  # length/hardware compare; they resolve CLI > baseline-embedded
  # `check_tolerances` block > defaults.
  python tools/goodput.py --check run_record.json \
      --baseline tools/goodput_baseline.json \
      [--ratio-tol 0.1] [--share-tol 0.1] [--tol stall=0.05 ...]

  # export empirical event-duration distributions (restart gaps,
  # checkpoint saves, init/compile, measured step times) for the fleet
  # digital twin (tools/fleetsim.py --distributions)
  python tools/goodput.py --distributions svrun/records -o dists.json

Semantics: docs/OBSERVABILITY.md "Goodput accounting" and
"Fleet digital twin".
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from distributed_neural_network_tpu.utils.goodput import (  # noqa: E402
    BADPUT_CAUSES,
    aggregate_records_dir,
    breakdown_from_trace,
    check_record,
    diff_records,
    extract_distributions,
    render_record,
    validate_record,
)


def load_input(path: str) -> dict:
    """Load a run record OR a Chrome trace (auto-detected: a doc with
    ``traceEvents`` is a trace and the taxonomy is derived from its
    spans; anything else must validate as a run record). A DIRECTORY
    aggregates its per-worker ``gen{g}_rank{r}.json`` records on the fly
    - the render path for a run that crashed before the supervisor wrote
    the fleet ``run_record.json``."""
    if os.path.isdir(path):
        return aggregate_records_dir(path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise ValueError(f"{path}: {e}")
    except json.JSONDecodeError as e:
        raise ValueError(f"{path}: not valid JSON ({e})")
    if isinstance(doc, dict) and "traceEvents" in doc:
        derived = breakdown_from_trace(doc)
        derived["source"] = "trace"
        # a trace exported by a ledger-armed run embeds the authoritative
        # record; keep it alongside for the cross-check view
        if isinstance(doc.get("goodput"), dict):
            derived["embedded_record"] = doc["goodput"]
        return derived
    return validate_record(doc, what=path)


def _collect_records(paths) -> list:
    """Record dicts from files and/or directories (a directory
    contributes every readable per-worker record under it / its
    ``records`` subdir, plus a fleet ``run_record.json`` if present -
    but not both channels' duplicates: rank records win)."""
    records = []
    for path in paths:
        if os.path.isdir(path):
            d = path
            sub = os.path.join(path, "records")
            if os.path.isdir(sub):
                d = sub
            found = []
            for name in sorted(os.listdir(d)):
                if not name.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(d, name)) as f:
                        found.append(validate_record(json.load(f), name))
                except (OSError, ValueError):
                    continue
            if not found:
                raise ValueError(f"{path}: no readable run records")
            records.extend(found)
            # the supervisor's fleet record adds the restart gaps the
            # rank records cannot know; its pooled events are skipped
            # (the ranks above already contributed them)
            fleet_path = os.path.join(path, "run_record.json")
            if d != path and os.path.isfile(fleet_path):
                try:
                    fleet = validate_record(
                        json.load(open(fleet_path)), fleet_path
                    )
                    records.append({
                        "version": fleet["version"],
                        "kind": "fleet",
                        "wall_s": 0.0,
                        "badput_s": {},
                        "restart_gaps": fleet.get("restart_gaps") or [],
                    })
                except (OSError, ValueError, KeyError):
                    pass
        else:
            records.append(load_input(path))
    return records


def _parse_cause_tols(pairs) -> dict:
    out = {}
    for pair in pairs or ():
        if "=" not in pair:
            raise ValueError(
                f"--tol wants cause=share, got {pair!r} "
                f"(causes: {', '.join(BADPUT_CAUSES)})"
            )
        cause, val = pair.split("=", 1)
        try:
            out[cause.strip()] = float(val)
        except ValueError:
            raise ValueError(f"--tol {pair!r}: {val!r} is not a number")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("record", nargs="?",
                   help="run record / fleet record / Chrome trace JSON")
    p.add_argument("--diff", nargs=2, metavar=("A", "B"),
                   help="compare two records (or traces) side by side")
    p.add_argument("--check", metavar="RECORD",
                   help="gate RECORD against --baseline; exit 1 on "
                   "regression")
    p.add_argument("--baseline", metavar="BASELINE.json",
                   help="the checked-in baseline record for --check "
                   "(may embed a check_tolerances block)")
    p.add_argument("--ratio-tol", type=float, default=None,
                   help="max allowed absolute DROP of goodput_ratio vs "
                   "the baseline (default: baseline-embedded, else 0.10)")
    p.add_argument("--share-tol", type=float, default=None,
                   help="max allowed absolute GROWTH of any badput "
                   "cause's wall-clock share (default: baseline-"
                   "embedded, else 0.10)")
    p.add_argument("--tol", action="append", metavar="CAUSE=SHARE",
                   help="per-cause share tolerance override "
                   "(repeatable), e.g. --tol stall=0.05")
    p.add_argument("--distributions", nargs="+", metavar="RECORD_OR_DIR",
                   help="export empirical event-duration distributions "
                   "(the fleet digital twin's inputs: restart gaps, "
                   "checkpoint saves, init/compile, step times) from "
                   "records and/or run dirs")
    p.add_argument("-o", "--out", metavar="OUT.json",
                   help="--distributions: write the document here "
                   "instead of stdout")
    args = p.parse_args(argv)

    modes = sum(bool(x) for x in (args.record, args.diff, args.check,
                                  args.distributions))
    if modes != 1:
        p.print_usage(sys.stderr)
        print("goodput: give exactly one of RECORD, --diff A B, "
              "--check RECORD --baseline BASE, or --distributions ...",
              file=sys.stderr)
        return 2

    try:
        if args.distributions:
            doc = extract_distributions(
                _collect_records(args.distributions)
            )
            blob = json.dumps(doc, indent=1, sort_keys=True)
            if args.out:
                with open(args.out, "w") as f:
                    f.write(blob + "\n")
                causes = ", ".join(
                    f"{c}({v['count']})" for c, v in doc["causes"].items()
                )
                print(f"(distributions from {doc['n_records']} record(s) "
                      f"-> {args.out}: {causes or 'no events'})")
            else:
                print(blob)
            return 0
        if args.diff:
            a, b = (load_input(x) for x in args.diff)
            print(diff_records(a, b, os.path.basename(args.diff[0]),
                               os.path.basename(args.diff[1])))
            return 0
        if args.check:
            if not args.baseline:
                print("goodput: --check requires --baseline", file=sys.stderr)
                return 2
            current = load_input(args.check)
            baseline = load_input(args.baseline)
            problems = check_record(
                current, baseline,
                ratio_tol=args.ratio_tol, share_tol=args.share_tol,
                cause_tols=_parse_cause_tols(args.tol),
            )
            print(render_record(
                current, title=f"Goodput check: {args.check} vs "
                f"baseline {args.baseline}"
            ))
            if problems:
                print(f"\nGOODPUT CHECK FAILED ({len(problems)} "
                      "regression(s)):")
                for prob in problems:
                    print(f"  - {prob}")
                print("\nIf the regression is intended (new workload "
                      "shape), regenerate the baseline record and commit "
                      "it with the change that moved the breakdown.")
                return 1
            print("\ngoodput check OK (within tolerances)")
            return 0
        rec = load_input(args.record)
        print(render_record(rec))
        if rec.get("embedded_record"):
            print()
            print(render_record(
                rec["embedded_record"],
                title="Embedded ledger record (authoritative; table "
                "above is span-derived)",
            ))
        return 0
    except ValueError as e:
        print(f"goodput: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
