#!/usr/bin/env python
"""autoshard CLI: static cost-model sharding search on CPU.

For each model scenario (the searchable shardlint configs,
analysis/configs.py), enumerates every mesh factorization of the device
count, derives candidate PartitionSpecs from the declarative rule table
(parallel/rules.py), abstract-traces each candidate step with the
shardlint tracer, scores it with the static cost model (analysis/cost.py:
ring-weighted collective wire bytes + per-device state memory vs the HBM
budget + donation coverage + replication-leak penalties), and ranks the
feasible plans. Nothing executes - the search is jaxpr tracing only.

Usage:
  python tools/autoshard.py --list
  python tools/autoshard.py --all --check            # the CI gate
  python tools/autoshard.py --model lm_dp --explain  # ranked plans + why
  python tools/autoshard.py --model lm_dp,lm_tp --devices 8
  python tools/autoshard.py --model lm_zero --optimizers sgd,zero
  python tools/autoshard.py --all --write-manifest   # pin the winners

Exit codes: 0 conforming; 1 plan drift or missing plan manifest; 2 a
search failed or an unknown --model name (the known list is printed).
See docs/STATIC_ANALYSIS.md ("Autoshard").
"""

import argparse
import os
import sys


def _force_cpu_mesh():
    """8 virtual CPU devices, set BEFORE jax import (the repo-standard
    test mesh - same bootstrap as tools/shardlint.py)."""
    flag = "--xla_force_host_platform_device_count=8"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "jax" in sys.modules:
        import jax

        try:  # re-assert against site hooks that pre-import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "--model", "--config", dest="model", action="append", default=[],
        help="model scenario name(s): repeatable and/or comma-separated; "
        "see --list",
    )
    ap.add_argument(
        "--all", action="store_true", help="every searchable config"
    )
    ap.add_argument(
        "--list", action="store_true",
        help="list searchable configs and exit",
    )
    ap.add_argument(
        "--devices", type=int, default=None, metavar="N",
        help="device count to factorize (default: the config's canonical "
        "mesh size)",
    )
    ap.add_argument(
        "--explain", action="store_true",
        help="print the full ranked plan table and the winner's per-term "
        "cost breakdown",
    )
    ap.add_argument(
        "--optimizers", default=None, metavar="A,B",
        help="widen the optimizer-layout dimension of the search (e.g. "
        "sgd,zero scores the cross-replica weight-update sharding "
        "against the replicated update; default: the scenario's own "
        "optimizer only)",
    )
    ap.add_argument(
        "--hbm-gb", type=float, default=None, metavar="GB",
        help="per-device HBM budget for the memory feasibility gate "
        "(default 16)",
    )
    ap.add_argument(
        "--precision", default=None, metavar="DTYPE",
        choices=("bf16", "int8", "fp8"),
        help="price the PARAM footprint as if stored in this dtype "
        "(per-block scales charged; optimizer state stays wide) - the "
        "quantized-footprint view of the HBM gate, so the search can "
        "trade precision for parallelism (analysis/cost.py "
        "DTYPE_BYTES). Recorded in written plan manifests; --check "
        "refuses to compare across precisions",
    )
    ap.add_argument(
        "--write-manifest", action="store_true",
        help="pin each search's winning plan as analysis/plans/<name>.json",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="re-search and fail if any top-ranked plan drifted from its "
        "checked-in plan manifest",
    )
    ap.add_argument(
        "--plan-dir", default=None,
        help="plan-manifest directory (default: the in-package "
        "analysis/plans)",
    )
    ap.add_argument(
        "-q", "--quiet", action="store_true",
        help="verdict lines only (no ranking tables)",
    )
    args = ap.parse_args(argv)

    _force_cpu_mesh()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    from distributed_neural_network_tpu.analysis import autoshard
    from distributed_neural_network_tpu.analysis.configs import (
        searchable_config_names,
    )
    from distributed_neural_network_tpu.analysis.cost import CostWeights

    known = searchable_config_names()
    if args.list:
        for name in known:
            print(name)
        return 0
    if args.write_manifest and args.check:
        ap.error("--write-manifest and --check are mutually exclusive")
    requested = [n for entry in args.model for n in entry.split(",") if n]
    unknown = [n for n in requested if n not in known]
    if unknown:
        print(
            f"unknown autoshard config(s): {', '.join(unknown)}\n"
            f"searchable configs: {', '.join(known)}"
        )
        return 2
    names = known if args.all or not requested else requested
    mode = (
        "write" if args.write_manifest else "check" if args.check else "rank"
    )
    weights = None
    if args.hbm_gb is not None or args.precision is not None:
        kw = {}
        if args.hbm_gb is not None:
            kw["hbm_bytes"] = int(args.hbm_gb * 2**30)
        if args.precision is not None:
            kw["param_precision"] = args.precision
        weights = CostWeights(**kw)
    optimizers = (
        tuple(o for o in args.optimizers.split(",") if o)
        if args.optimizers else None
    )
    rc, report = autoshard.run_autoshard(
        names, mode=mode, plan_dir=args.plan_dir, devices=args.devices,
        explain=args.explain, optimizers=optimizers, weights=weights,
        verbose=not args.quiet,
    )
    print(report)
    return rc


if __name__ == "__main__":
    sys.exit(main())
