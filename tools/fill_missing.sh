#!/bin/bash
# Follow-up measurement session: re-tune with the RTT-corrected timer and
# fill every accelerator row the first pass lost to the wedge, under the
# NEW single-claim group worker (bench.py --worker-multi; --only forces
# re-measurement). Refuses to start while measure_all/bench is running
# (two claimers wedge the chip), then probes patiently - a probe against
# a wedged claim blocks tens of minutes before erroring, which IS the
# polling interval; probes are never killed by this script.
# Run detached:  setsid nohup bash tools/fill_missing.sh \
#                    > fill_missing.log 2>&1 &
set -u
cd "$(dirname "$0")/.."

ROWS="cnn_dp_ep25_bs32,cnn_dp_ep25_bs64,cnn_dp_ep25_bs16_pallas"
ROWS="$ROWS,cnn_dp_ep25_bs16_bf16,cnn_dp_ep25_bs16_stream"
ROWS="$ROWS,lm_flash_d512_L8_seq2048_bf16,lm_flashlib_d512_L8_seq2048_bf16"
ROWS="$ROWS,lm_flash_d512_L8_seq2048_bf16_hd128"
ROWS="$ROWS,lm_xla_d512_L8_seq2048_bf16_remat"
ROWS="$ROWS,lm_flash_d1024_L16_seq2048_bf16"
ROWS="$ROWS,lm_xla_d512_L8_seq2048_bf16_rematattn"
ROWS="$ROWS,lm_flash_d1024_L16_seq2048_bf16_remat_b8"
ROWS="$ROWS,lm_flash_d512_L8_seq8192_bf16,lm_decode_d512_L8_b16_bf16"

# match ANY bench/tune invocation (a parent in its probe/backoff window
# has no --worker child yet, and a plain `bench.py --refresh` has no
# --deadline flag - missing those would start a second claimer). The
# pattern is ANCHORED to a python first token: an unanchored
# "bench\.py" also matches the build driver, whose argv embeds prompt
# text naming these files, and the gate would never open
while pgrep -f "^[^ ]*python[0-9.]* [^ ]*(bench|tune_flash|measure_all)\.py" \
    > /dev/null; do
  echo "[fill] a measurement session is still running; sleeping 120s"
  sleep 120
done

attempt=0
while true; do
  attempt=$((attempt + 1))
  echo "[fill] probe attempt ${attempt} at $(date -u +%H:%M:%S)"
  if python -c "
import time, jax, jax.numpy as jnp
t0 = time.time()
x = jnp.ones((512, 512), jnp.bfloat16)
v = float((x @ x).sum())
print('probe ok: value', v, 'in', round(time.time() - t0, 1), 's', flush=True)
"; then
    echo "[fill] chip healthy at $(date -u +%H:%M:%S) - re-tuning (RTT-corrected)"
    python tools/tune_flash.py; rc1=$?
    python tools/tune_flash.py --heads 4 --head-dim 128; rc2=$?
    if [ "$rc1" -ne 0 ] || [ "$rc2" -ne 0 ]; then
      echo "[fill] WARNING: tune rc=${rc1}/${rc2} - LM rows will run on" \
           "whatever tune files exist (possibly stale pre-RTT-fix blocks)"
    else
      echo "[fill] tunes done - filling rows (one claim)"
    fi
    python bench.py --only "$ROWS" --deadline 14400
    echo "[fill] bench rc=$? - rendering report"
    python report.py --from-matrix
    echo "[fill] done rc=$? at $(date -u +%H:%M:%S)"
    break
  fi
  echo "[fill] probe failed; sleeping 180s before the next attempt"
  sleep 180
done
