#!/bin/bash
# STATUS (r5, 2026-08-01): the fill pass COMPLETED on the first
# healthy chip - all four stages landed and their artifacts are
# committed (flagship row, parity check ok, RTT-corrected tunes,
# every error row). Re-running this script is safe but re-measures
# its --only row lists; for routine round-end measurement use
# `python bench.py` (keep-measured mode) instead.
#
# Fill measurement session: on the first healthy chip, run the on-TPU
# kernel-numerics parity check, re-measure the flagship LM row with the
# already-tuned flash blocks (the r4 11.81 ms/layer config - the >=40%
# MFU claim lands or falls on this row, so it goes FIRST), then the
# RTT-corrected tunes, then every remaining error row, under the
# single-claim group worker (bench.py --worker-multi; --only forces
# re-measurement). Artifacts are committed as each stage lands so a
# relay death or session end cannot lose measured data again (r4 lost
# tune files exactly that way).
#
# Gate design (r4 VERDICT items 1-2): the cheap TCP relay gate
# (tools/relay_up.py) runs INSIDE the probe loop, so a relay death at
# any point between probes costs a 60 s poll, not a ~50 min blocked jax
# RPC. rc 2 = the gate itself crashed - fall through to the real probe
# rather than pinning at "down". Probes are never killed (killing a
# claimer wedges the chip); a probe against a wedged claim blocks
# 30-50 min before erroring, which IS the polling interval.
#
# Run detached:  setsid nohup bash tools/fill_missing.sh \
#                    > fill_missing.log 2>&1 &
set -u
cd "$(dirname "$0")/.."

# single-instance lock (shared with watch_and_measure.sh): two
# gate-synchronized chip watchers would fire claimers at the same
# gate-open instant - the r4 wedge condition. flock covers every copy of
# either script (and survives bash's incremental script reads, which a
# pgrep self-exclusion would not).
exec 9>".chip_session.lock"
if ! flock -n 9; then
  echo "[fill] another chip watcher holds the lock; waiting for it"
  flock 9
  echo "[fill] lock acquired at $(date -u +%H:%M:%S)"
fi

ROWS="cnn_dp_ep25_bs32,cnn_dp_ep25_bs64,cnn_dp_ep25_bs16_pallas"
ROWS="$ROWS,cnn_dp_ep25_bs16_bf16,cnn_dp_ep25_bs16_stream"
ROWS="$ROWS,lm_flash_d512_L8_seq2048_bf16,lm_flashlib_d512_L8_seq2048_bf16"
ROWS="$ROWS,lm_flash_d512_L8_seq2048_bf16_hd128"
ROWS="$ROWS,lm_xla_d512_L8_seq2048_bf16_remat"
ROWS="$ROWS,lm_flash_d1024_L16_seq2048_bf16"
ROWS="$ROWS,lm_xla_d512_L8_seq2048_bf16_rematattn"
ROWS="$ROWS,lm_flash_d1024_L16_seq2048_bf16_remat_b8"
ROWS="$ROWS,lm_flash_d512_L8_seq8192_bf16,lm_decode_d512_L8_b16_bf16"
# the flagship row runs alone first (highest-leverage, r4 VERDICT item
# 1); it stays in ROWS too so it re-measures after the fresh tunes -
# merge-by-id keeps the newest record.
FLAGSHIP="lm_flash_d512_L8_seq2048_bf16"

# commit measured artifacts immediately (retry: the interactive session
# may hold .git/index.lock briefly). Pathspecs are QUOTED (git expands
# them and silently skips ignored files like tools/measure_all_log.json;
# a shell-expanded ignored path makes git add exit 1) and the commit is
# pathspec-limited so anything the interactive session pre-staged is
# left alone. An unchanged tree is a no-op, not a failure.
commit_artifacts() {
  local msg="$1"
  local paths=("tools/*.json" "BENCH_MATRIX.json" "REPORT.md")
  for i in 1 2 3; do
    if git add -- "${paths[@]}" 2>/dev/null; then
      if git diff --cached --quiet -- "${paths[@]}"; then
        echo "[fill] nothing new to commit for: $msg"
        return 0
      fi
      if git commit --quiet -m "$msg" -- "${paths[@]}" 2>/dev/null; then
        echo "[fill] committed: $msg"
        return 0
      fi
    fi
    sleep 5
  done
  echo "[fill] commit failed (non-fatal): $msg"
  return 0
}

# match ANY bench/tune/parity invocation (a parent in its probe/backoff
# window has no --worker child yet, and a plain `bench.py --refresh` has
# no --deadline flag - missing those would start a second claimer). The
# pattern is ANCHORED to a python first token: an unanchored "bench\.py"
# also matches the build driver, whose argv embeds prompt text naming
# these files, and the gate would never open. The second pgrep catches a
# LEGACY watcher surviving from a pre-flock session while it is actively
# probing ("probe ok: value" is the probe python's own argv); a legacy
# watcher sleeping between probes is invisible here - bounded residual
# race, gone once every live copy takes .chip_session.lock.
while pgrep -f "^[^ ]*python[0-9.]* [^ ]*(bench|tune_flash|measure_all|flash_parity_check)\.py" \
    > /dev/null \
    || pgrep -f "probe ok: value" > /dev/null; do
  echo "[fill] a measurement session is still running; sleeping 120s"
  sleep 120
done

attempt=0
while true; do
  attempt=$((attempt + 1))
  # cheap TCP gate first: with the relay dead (r4 post-mortem), a jax
  # probe blocks ~50 min in RPC retries; this check costs milliseconds
  # and holds no claim, so the poll interval stays 60 s while the
  # transport is down. rc 2 = gate crashed - fall through to the probe.
  gate_out=$(python tools/relay_up.py 2>&1); gate_rc=$?
  if [ "$gate_rc" -eq 1 ]; then
    if [ $((attempt % 30)) -eq 1 ]; then
      echo "[fill] relay down (attempt ${attempt}) at $(date -u +%H:%M:%S)"
    fi
    sleep 60
    continue
  elif [ "$gate_rc" -ne 0 ]; then
    echo "[fill] relay gate unusable (rc ${gate_rc}): ${gate_out} - falling through to the jax probe"
  fi
  echo "[fill] probe attempt ${attempt} at $(date -u +%H:%M:%S)"
  if python -c "
import time, jax, jax.numpy as jnp
t0 = time.time()
x = jnp.ones((512, 512), jnp.bfloat16)
v = float((x @ x).sum())
print('probe ok: value', v, 'in', round(time.time() - t0, 1), 's', flush=True)
"; then
    echo "[fill] chip healthy at $(date -u +%H:%M:%S)"

    # claim-cycle budget (r4: a hang was observed on the 4th consecutive
    # claim/release cycle): highest-leverage stage takes the FIRST claim
    # so a later wedge cannot cost it.
    echo "[fill] stage 1: flagship LM row with the tuned flash blocks"
    python bench.py --only "$FLAGSHIP" --deadline 3600
    echo "[fill] flagship rc=$?"
    commit_artifacts "measure: flagship LM row with tuned flash blocks"

    echo "[fill] stage 2: on-TPU kernel numerics parity"
    python tools/flash_parity_check.py; rc=$?
    echo "[fill] parity rc=${rc}"
    commit_artifacts "measure: on-TPU kernel numerics parity (rc=${rc})"

    echo "[fill] stage 3: re-tune flash (RTT-corrected timer)"
    python tools/tune_flash.py; rc1=$?
    python tools/tune_flash.py --heads 4 --head-dim 128; rc2=$?
    if [ "$rc1" -ne 0 ] || [ "$rc2" -ne 0 ]; then
      echo "[fill] WARNING: tune rc=${rc1}/${rc2} - LM rows will run on" \
           "whatever tune files exist"
    fi
    commit_artifacts "measure: flash tunes hd64/hd128 (rc=${rc1}/${rc2})"

    echo "[fill] stage 4: filling all rows (one claim)"
    python bench.py --only "$ROWS" --deadline 14400
    echo "[fill] bench rc=$? - rendering report"
    python report.py --from-matrix
    echo "[fill] report rc=$?"
    commit_artifacts "measure: fill pass rows + report re-render"
    echo "[fill] done at $(date -u +%H:%M:%S)"
    break
  fi
  echo "[fill] probe failed; sleeping 180s before the next attempt"
  sleep 180
done
