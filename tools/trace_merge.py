#!/usr/bin/env python
"""trace_merge: merge per-rank Chrome trace shards into one fleet timeline.

A supervised multi-process run (`tools/launch.py` + `lm_train.py
--trace-out trace.json`) writes one trace shard per worker -
``trace_rank0.json``, ``trace_rank1.json``, ... (`utils/tracing.py
rank_trace_path`). Each shard's timestamps are microseconds since ITS OWN
tracer epoch (a per-process `perf_counter` origin), so loading two shards
side by side in Perfetto puts both at t=0 and every cross-rank comparison
lies. This tool merges N shards into ONE Perfetto document with:

- **clock alignment** - every shard records its epoch as Unix time
  (``otherData.epoch_unix``, the same wall clock the rendezvous/heartbeat
  files stamp); the merge rebases all events onto the earliest shard's
  epoch, so "the same wall moment" lands at the same x position. The
  per-rank offsets are recorded in the merged ``otherData.clock_offsets_s``
  (and printable with --summary). Cross-HOST shards inherit whatever NTP
  skew the hosts have; single-node groups (the supervisor's domain) share
  one clock exactly.
- **rank-stable process lanes** - each shard becomes one Perfetto process
  whose pid IS the rank and whose ``process_name`` is ``rank{N}`` (the
  tracer stamps it; the merge falls back to the filename), so merged
  timelines stay readable across supervisor relaunches where pids change.
- **per-step alignment markers** - for every step index that appears as a
  ``train_step`` span in two or more shards, one global ``step_align``
  instant at the earliest rank's span end, with the cross-rank end-time
  skew and the last-finishing (straggler) rank in its args: stragglers
  are visible as ragged step boundaries without squinting at spans.

Per-rank ``stepStats`` embeds are preserved under ``rankStepStats`` (keyed
by rank) so `tools/trace_summary.py --rank N` still reports them.

Usage:
  python tools/trace_merge.py trace_rank0.json trace_rank1.json -o merged.json
  python tools/trace_merge.py svrun/trace_rank*.json -o merged.json --summary
  python tools/trace_summary.py merged.json --rank 1

Stdlib-only (no jax, no repo imports) - runs anywhere, like the other
trace tools.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from collections import defaultdict


def _reject_constant(name: str):
    raise ValueError(
        f"non-strict JSON token {name!r} (bare NaN/Infinity); the writer "
        "must serialize non-finite floats as null"
    )


def load_shard(path: str) -> dict:
    with open(path) as f:
        doc = json.loads(f.read(), parse_constant=_reject_constant)
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path}: not a Chrome trace-event document")
    return doc


def shard_rank(doc: dict, path: str, fallback: int) -> int:
    """Rank of one shard: otherData.rank, else the process_name metadata
    (``rank{N}``), else a ``rank{N}`` filename component, else the
    position in the argument list."""
    other = doc.get("otherData") or {}
    if isinstance(other.get("rank"), int):
        return other["rank"]
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            m = re.fullmatch(r"rank(\d+)", str((ev.get("args") or {}).get("name", "")))
            if m:
                return int(m.group(1))
    m = re.search(r"rank(\d+)", os.path.basename(path))
    if m:
        return int(m.group(1))
    return fallback


def merge_shards(
    shards: list[tuple[str, dict]], *, align: str = "epoch"
) -> dict:
    """Merge [(path, doc), ...] into one aligned Chrome document."""
    ranks: list[int] = []
    for i, (path, doc) in enumerate(shards):
        r = shard_rank(doc, path, i)
        while r in ranks:  # duplicate rank labels must not collide
            r = max(ranks) + 1
        ranks.append(r)

    # ---- clock alignment: rebase every shard onto the earliest epoch
    epochs = [
        (doc.get("otherData") or {}).get("epoch_unix")
        for _, doc in shards
    ]
    base = min(
        (e for e in epochs if isinstance(e, (int, float))), default=None
    )
    offsets: dict[int, float] = {}
    unaligned: list[int] = []
    for r, e in zip(ranks, epochs):
        if align == "epoch" and base is not None \
                and isinstance(e, (int, float)):
            offsets[r] = float(e) - float(base)
        else:
            offsets[r] = 0.0
            if align == "epoch":
                unaligned.append(r)

    events: list[dict] = []
    rank_stats: dict[str, dict] = {}
    step_spans: dict[int, dict[int, dict]] = defaultdict(dict)
    for (path, doc), r in zip(shards, ranks):
        off_us = offsets[r] * 1e6
        other = doc.get("otherData") or {}
        hostname = other.get("hostname")
        # a shard with no rank identity and a custom process label (a
        # serving trace's "serve:8000" request lanes) keeps its label -
        # rewriting it to rankN would mislabel a non-rank process
        keep_label = not isinstance(other.get("rank"), int)
        stats = doc.get("stepStats")
        if isinstance(stats, dict) and stats:
            rank_stats[str(r)] = stats
        seen_pname = False
        for ev in doc.get("traceEvents", []):
            out = dict(ev)
            out["pid"] = r  # rank-stable lane, not the dead worker's pid
            if ev.get("ph") == "M":
                if ev.get("name") == "process_name":
                    seen_pname = True
                    args = dict(ev.get("args") or {})
                    orig = str(args.get("name", ""))
                    if keep_label and orig and not re.fullmatch(
                        r"rank\d+", orig
                    ) and orig != "dnn-tpu-train":
                        label = orig
                    else:
                        label = f"rank{r}"
                    args["name"] = label + (
                        f" ({hostname})" if hostname else ""
                    )
                    out["args"] = args
                events.append(out)
                continue
            if "ts" in out:
                out["ts"] = float(out["ts"]) + off_us
            events.append(out)
            if ev.get("ph") == "X" and ev.get("name") == "train_step":
                step = (ev.get("args") or {}).get("step")
                if isinstance(step, int):
                    end = float(out["ts"]) + float(out.get("dur", 0.0))
                    step_spans[step][r] = {
                        "start_us": float(out["ts"]), "end_us": end,
                        "dur_us": float(out.get("dur", 0.0)),
                    }
        if not seen_pname:
            events.append({
                "name": "process_name", "ph": "M", "pid": r, "tid": 0,
                "ts": 0, "args": {"name": f"rank{r}"},
            })
        # rank ordering in the Perfetto process list
        events.append({
            "name": "process_sort_index", "ph": "M", "pid": r, "tid": 0,
            "ts": 0, "args": {"sort_index": r},
        })

    # ---- per-step alignment markers + skew stats
    skews: list[tuple[int, float, int]] = []  # (step, skew_s, straggler)
    for step in sorted(step_spans):
        by_rank = step_spans[step]
        if len(by_rank) < 2:
            continue
        ends = {r: v["end_us"] for r, v in by_rank.items()}
        straggler = max(ends, key=lambda r: ends[r])
        skew_us = max(ends.values()) - min(ends.values())
        skews.append((step, skew_us / 1e6, straggler))
        events.append({
            "name": "step_align", "ph": "i", "s": "g",
            "pid": min(by_rank), "tid": 0,
            "ts": min(ends.values()),
            "cat": "fleet",
            "args": {
                "step": step,
                "end_skew_us": round(skew_us, 1),
                "straggler_rank": straggler,
                "ranks": sorted(by_rank),
            },
        })

    events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    fleet = {
        "ranks": sorted(ranks),
        "aligned_steps": len(skews),
        "max_step_skew_s": round(max((s for _, s, _ in skews),
                                     default=0.0), 6),
        "straggler_rank": (
            max(
                set(r for _, _, r in skews),
                key=lambda r: sum(
                    s for _, s, rr in skews if rr == r
                ),
            ) if skews else None
        ),
    }
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": len(shards),
            "ranks": sorted(ranks),
            "align": align,
            "base_epoch_unix": base,
            "clock_offsets_s": {
                str(r): round(o, 6) for r, o in offsets.items()
            },
            "unaligned_ranks": unaligned,
        },
        "fleet": fleet,
        "rankStepStats": rank_stats,
    }


def summarize(doc: dict) -> str:
    """Per-rank step table + skew summary of a merged document."""
    spans: dict[int, list[float]] = defaultdict(list)
    skews: list[float] = []
    straggles: dict[int, int] = defaultdict(int)
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "X" and ev.get("name") == "train_step":
            spans[ev.get("pid")].append(float(ev.get("dur", 0.0)) / 1e6)
        elif ev.get("ph") == "i" and ev.get("name") == "step_align":
            args = ev.get("args") or {}
            skews.append(float(args.get("end_skew_us", 0.0)) / 1e6)
            if args.get("straggler_rank") is not None:
                straggles[args["straggler_rank"]] += 1
    lines = []
    other = doc.get("otherData") or {}
    lines.append(
        f"Merged timeline: {other.get('merged_from')} shard(s), ranks "
        f"{other.get('ranks')}, clock offsets "
        f"{other.get('clock_offsets_s')} s"
    )
    if other.get("unaligned_ranks"):
        lines.append(
            f"  WARNING: rank(s) {other['unaligned_ranks']} had no "
            "epoch_unix - left unaligned (offset 0)"
        )
    head = f"{'rank':>5}  {'steps':>6}  {'mean_ms':>9}  {'p95_ms':>9}  {'straggled':>9}"
    lines += [head, "-" * len(head)]
    for r in sorted(spans):
        xs = sorted(spans[r])
        p95 = xs[max(0, min(len(xs) - 1,
                            int(math.ceil(0.95 * len(xs))) - 1))]
        lines.append(
            f"{r:>5}  {len(xs):>6}  {sum(xs) / len(xs) * 1e3:>9.2f}  "
            f"{p95 * 1e3:>9.2f}  {straggles.get(r, 0):>9}"
        )
    if skews:
        lines.append(
            f"step-boundary skew: {len(skews)} aligned step(s), max "
            f"{max(skews) * 1e3:.1f} ms, mean "
            f"{sum(skews) / len(skews) * 1e3:.1f} ms"
        )
        fleet = doc.get("fleet") or {}
        if fleet.get("straggler_rank") is not None:
            lines.append(
                f"dominant straggler: rank {fleet['straggler_rank']} "
                "(largest summed end-skew)"
            )
    else:
        lines.append(
            "step-boundary skew: n/a (no step appears in >= 2 shards)"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "shards", nargs="+",
        help="two or more per-rank trace shards (trace_rank*.json)",
    )
    ap.add_argument(
        "-o", "--out", default="merged_trace.json",
        help="merged Perfetto document path (default merged_trace.json)",
    )
    ap.add_argument(
        "--align", choices=("epoch", "none"), default="epoch",
        help="clock alignment: 'epoch' (default) rebases each shard by "
        "its recorded Unix epoch; 'none' keeps raw per-shard clocks",
    )
    ap.add_argument(
        "--summary", action="store_true",
        help="print the per-rank step table + skew summary",
    )
    args = ap.parse_args(argv)
    if len(args.shards) < 2:
        print("error: need at least two shards to merge", file=sys.stderr)
        return 2
    shards = []
    for path in args.shards:
        try:
            shards.append((path, load_shard(path)))
        except (OSError, ValueError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    doc = merge_shards(shards, align=args.align)
    d = os.path.dirname(args.out)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, allow_nan=False)
        f.write("\n")
    os.replace(tmp, args.out)
    print(
        f"(merged {len(shards)} shard(s) -> {args.out}; open in Perfetto, "
        "or tools/trace_summary.py [--rank N])"
    )
    if args.summary:
        print(summarize(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
