#!/usr/bin/env python
"""Reference-scale oracle trajectory parity: the round-4 accuracy-claim
evidence artifact (r3 VERDICT item 5).

Real CIFAR-10 does not exist in this environment (no files, no egress), so
the 63-66% accuracy band (BASELINE.md, Project_Report.pdf section 5) cannot
be reproduced directly. What CAN be proven is stronger than a smoke test:
that the engine computes the reference's exact algorithm at the
reference's exact scale - 25 epochs x 50,000 training rows x 8 workers x
batch 16 (Table 1's row count and epoch count) - by matching the
pure-numpy oracle (tests/oracle_numpy.py) epoch by epoch on parameters and
global train loss. On real data the trajectory, and therefore the accuracy
band, follows from the data alone.

Runs on the 8-virtual-device CPU mesh (JAX_PLATFORMS=cpu; no TPU claim -
this is an algorithm-identity check, not a perf measurement). Wall cost is
~1 h, dominated by the float64 numpy oracle; run detached:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/oracle_fullscale.py

Writes tools/oracle_fullscale_result.json: per-epoch oracle/engine train
loss, their abs diff, and the max param rel err - the drift curve of f32
XLA vs f64 numpy over the full 25-epoch horizon, which REPORT.md's
accuracy-parity section cites.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

EPOCHS = int(os.environ.get("ORACLE_EPOCHS", "25"))
ROWS = int(os.environ.get("ORACLE_ROWS", "50000"))
WORKERS = int(os.environ.get("ORACLE_WORKERS", "8"))
BATCH = 16
LR, MOMENTUM, SEED = 0.001, 0.9, 0


def _host_tree(t):
    import numpy as np

    return {k: _host_tree(v) if isinstance(v, dict) else np.asarray(v)
            for k, v in t.items()}


def _max_rel_err(a, b):
    import numpy as np

    worst = 0.0
    for k in a:
        if isinstance(a[k], dict):
            worst = max(worst, _max_rel_err(a[k], b[k]))
        else:
            x, y = np.asarray(a[k], np.float64), np.asarray(b[k], np.float64)
            worst = max(worst, float(
                np.abs(x - y).max() / max(np.abs(y).max(), 1e-12)
            ))
    return worst


def main() -> int:
    from distributed_neural_network_tpu.train.cli import honor_platform_env

    honor_platform_env()
    import jax
    import numpy as np

    assert jax.default_backend() == "cpu", (
        "run with JAX_PLATFORMS=cpu - this artifact must not claim the TPU"
    )
    from distributed_neural_network_tpu.data.cifar10 import load_split
    from distributed_neural_network_tpu.train.engine import Engine, TrainConfig
    from oracle_numpy import reference_trajectory, to_f64
    from test_oracle import _engine_orders

    t_start = time.time()
    split = load_split(True, source="synthetic", synthetic_size=ROWS, seed=3)
    cfg = TrainConfig(
        lr=LR, momentum=MOMENTUM, batch_size=BATCH, epochs=EPOCHS,
        regime="data_parallel", sync_mode="epoch", reset_momentum=True,
        seed=SEED, nb_proc=WORKERS,
    )
    eng = Engine(cfg, split, None)
    params0 = _host_tree(eng.params)
    orders = _engine_orders(SEED, EPOCHS, WORKERS, eng.local_train_rows)

    print(f"[oracle_fullscale] engine: {EPOCHS} epochs x {ROWS} rows x "
          f"{WORKERS} workers (bs {BATCH})", flush=True)
    engine_hist = []
    for e in range(EPOCHS):
        m = eng.run_epoch(e, do_eval=False)
        engine_hist.append(
            {"train_loss": float(m.train_loss), "params": _host_tree(eng.params)}
        )
        print(f"[oracle_fullscale] engine epoch {e}: loss {m.train_loss:.6f} "
              f"({time.time() - t_start:.0f}s)", flush=True)

    print("[oracle_fullscale] oracle (float64 numpy)...", flush=True)
    oracle_hist = reference_trajectory(
        to_f64(params0), split.images, split.labels, n_workers=WORKERS,
        batch_size=BATCH, epochs=EPOCHS, lr=LR, momentum=MOMENTUM,
        orders=orders, regime="data_parallel",
    )

    epochs_out, worst_loss, worst_param = [], 0.0, 0.0
    for e in range(EPOCHS):
        dl = abs(engine_hist[e]["train_loss"] - oracle_hist[e]["train_loss"])
        dp = _max_rel_err(engine_hist[e]["params"], oracle_hist[e]["params"])
        worst_loss, worst_param = max(worst_loss, dl), max(worst_param, dp)
        epochs_out.append({
            "epoch": e,
            "engine_loss": round(engine_hist[e]["train_loss"], 6),
            "oracle_loss": round(oracle_hist[e]["train_loss"], 6),
            "loss_abs_diff": round(dl, 6),
            "param_max_rel_err": round(dp, 6),
        })
        print(f"[oracle_fullscale] epoch {e}: engine "
              f"{engine_hist[e]['train_loss']:.6f} oracle "
              f"{oracle_hist[e]['train_loss']:.6f} dloss {dl:.2e} "
              f"dparam {dp:.2e}", flush=True)

    ok = worst_loss < 1e-2 and worst_param < 0.02
    out = {
        "scale": {"epochs": EPOCHS, "rows": ROWS, "workers": WORKERS,
                  "batch_size": BATCH, "lr": LR, "momentum": MOMENTUM},
        "ok": ok,
        "worst_loss_abs_diff": worst_loss,
        "worst_param_max_rel_err": worst_param,
        "note": (
            "engine = f32 XLA on the 8-device CPU mesh; oracle = f64 numpy "
            "(tests/oracle_numpy.py - the reference algorithm, "
            "/root/reference/data_parallelism_train.py:49-53,187-203,"
            "238-244). Diffs are float-precision drift of the SAME "
            "algorithm over the full horizon, not algorithmic divergence."
        ),
        "wall_s": round(time.time() - t_start, 1),
        "epochs": epochs_out,
    }
    path = os.path.join(REPO, "tools", "oracle_fullscale_result.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"[oracle_fullscale] ok={ok} worst dloss {worst_loss:.2e} worst "
          f"dparam {worst_param:.2e} -> {path} "
          f"({out['wall_s']:.0f}s)", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
