#!/usr/bin/env python
"""Plot training curves from a metrics JSONL file (utils/metrics.py sink).

The reference tracked its curves on Neptune's SaaS dashboard
(`single_proc_train.py:20-26`); this is the local, credential-free
equivalent: one PNG with the train/loss, val/loss and val/acc series of
any run written with --metrics-jsonl (LM) or the CNN engine's JSONL sink.

Usage: python tools/plot_metrics.py runs/lm.jsonl [-o curves.png]
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from collections import defaultdict


def load_series(path: str):
    """Parse a metrics JSONL file into {series: (steps, values)}.

    Malformed lines (a run killed mid-write leaves a truncated tail; older
    files may carry bare NaN tokens) are skipped and counted to stderr
    instead of crashing the plot; non-numeric values (the null a
    sanitized NaN/Inf serializes to, utils/metrics.py) are skipped too.
    """
    series = defaultdict(lambda: ([], []))
    params = None
    malformed = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                malformed += 1
                continue
            if not isinstance(ev, dict):
                malformed += 1
                continue
            if ev.get("series") == "parameters":
                params = ev.get("data")
                continue
            if "value" in ev and isinstance(ev.get("series"), str):
                v = ev["value"]
                if (
                    not isinstance(v, (int, float))
                    or isinstance(v, bool)
                    or not math.isfinite(v)
                ):
                    continue  # null/NaN/invalid sample: not plottable
                xs, ys = series[ev["series"]]
                xs.append(ev.get("step", len(xs)))
                ys.append(v)
    if malformed:
        print(
            f"({malformed} malformed JSONL line(s) skipped in {path})",
            file=sys.stderr,
        )
    return dict(series), params


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jsonl")
    ap.add_argument("-o", "--out", default=None,
                    help="output PNG (default: <jsonl>.png)")
    args = ap.parse_args()

    series, params = load_series(args.jsonl)
    if not series:
        print(f"no series events in {args.jsonl}", file=sys.stderr)
        return 1

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    loss_keys = [k for k in series if k.endswith("loss")]
    acc_keys = [k for k in series if k.endswith("acc")]
    n_axes = 1 + bool(acc_keys)
    fig, axes = plt.subplots(1, n_axes, figsize=(6 * n_axes, 4))
    axes = [axes] if n_axes == 1 else list(axes)

    for k in sorted(loss_keys):
        xs, ys = series[k]
        axes[0].plot(xs, ys, marker=".", label=k)
    axes[0].set_xlabel("step")
    axes[0].set_ylabel("loss")
    axes[0].legend()
    axes[0].grid(True, alpha=0.3)
    if acc_keys:
        for k in sorted(acc_keys):
            xs, ys = series[k]
            axes[1].plot(xs, ys, marker=".", label=k)
        axes[1].set_xlabel("step")
        axes[1].set_ylabel("accuracy (%)")
        axes[1].legend()
        axes[1].grid(True, alpha=0.3)
    if params:
        fig.suptitle(
            ", ".join(f"{k}={v}" for k, v in list(params.items())[:6]),
            fontsize=9,
        )
    out = args.out or args.jsonl + ".png"
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
