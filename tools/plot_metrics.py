#!/usr/bin/env python
"""Plot training curves from a metrics JSONL file (utils/metrics.py sink).

The reference tracked its curves on Neptune's SaaS dashboard
(`single_proc_train.py:20-26`); this is the local, credential-free
equivalent: one PNG with the train/loss, val/loss and val/acc series of
any run written with --metrics-jsonl (LM) or the CNN engine's JSONL sink.

Usage: python tools/plot_metrics.py runs/lm.jsonl [-o curves.png]
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from collections import defaultdict


def _num(v) -> bool:
    return (
        isinstance(v, (int, float))
        and not isinstance(v, bool)
        and math.isfinite(v)
    )


def load_series(path: str):
    """Parse a metrics JSONL file into {series: (steps, values)}.

    Malformed lines (a run killed mid-write leaves a truncated tail; older
    files may carry bare NaN tokens) are skipped and counted to stderr
    instead of crashing the plot; non-numeric values (the null a
    sanitized NaN/Inf serializes to, utils/metrics.py) are skipped too,
    as are non-numeric steps (a corrupted row must not poison the x axis).

    A --dynamics-jsonl stream (train/dynamics.py: one row per step with
    a ``layers`` object) is recognized too: its global health numbers fan
    out as dynamics/* series so the same tool plots both file kinds.
    """
    series = defaultdict(lambda: ([], []))
    params = None
    malformed = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                malformed += 1
                continue
            if not isinstance(ev, dict):
                malformed += 1
                continue
            if ev.get("series") == "parameters":
                params = ev.get("data")
                continue
            if isinstance(ev.get("layers"), dict) and _num(ev.get("step")):
                for key in ("grad_norm", "param_norm", "upd_ratio_max",
                            "layer_grad_norm_max"):
                    if _num(ev.get(key)):
                        xs, ys = series[f"dynamics/{key}"]
                        xs.append(ev["step"])
                        ys.append(ev[key])
                gns = ev.get("gns")
                if isinstance(gns, dict):
                    for key in ("noise_scale", "crit_batch_size"):
                        if _num(gns.get(key)):
                            xs, ys = series[f"dynamics/gns_{key}"]
                            xs.append(ev["step"])
                            ys.append(gns[key])
                continue
            if "value" in ev and isinstance(ev.get("series"), str):
                v = ev["value"]
                if not _num(v):
                    continue  # null/NaN/invalid sample: not plottable
                xs, ys = series[ev["series"]]
                step = ev.get("step")
                xs.append(step if _num(step) else len(xs))
                ys.append(v)
    if malformed:
        print(
            f"({malformed} malformed JSONL line(s) skipped in {path})",
            file=sys.stderr,
        )
    return dict(series), params


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("jsonl")
    ap.add_argument("-o", "--out", default=None,
                    help="output PNG (default: <jsonl>.png)")
    args = ap.parse_args()

    series, params = load_series(args.jsonl)
    if not series:
        print(f"no series events in {args.jsonl}", file=sys.stderr)
        return 1

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    loss_keys = [k for k in series if k.endswith("loss")]
    acc_keys = [k for k in series if k.endswith("acc")]
    dyn_keys = [k for k in series if k.startswith("dynamics/")]
    # one panel per populated group; norms/ratios span orders of
    # magnitude, so the dynamics panel is log-scaled
    panels = [(sorted(loss_keys), "loss", False)]
    if acc_keys:
        panels.append((sorted(acc_keys), "accuracy (%)", False))
    if dyn_keys:
        panels.append((sorted(dyn_keys), "norm / ratio", True))
    n_axes = len(panels)
    fig, axes = plt.subplots(1, n_axes, figsize=(6 * n_axes, 4))
    axes = [axes] if n_axes == 1 else list(axes)

    for ax, (keys, ylabel, log_y) in zip(axes, panels):
        for k in keys:
            xs, ys = series[k]
            ax.plot(xs, ys, marker=".", label=k)
        ax.set_xlabel("step")
        ax.set_ylabel(ylabel)
        if keys:
            ax.legend()
        if log_y:
            ax.set_yscale("log")
        ax.grid(True, alpha=0.3)
    if params:
        fig.suptitle(
            ", ".join(f"{k}={v}" for k, v in list(params.items())[:6]),
            fontsize=9,
        )
    out = args.out or args.jsonl + ".png"
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
