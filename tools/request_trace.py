#!/usr/bin/env python
"""request_trace: per-request tail attribution + SLO gates for the
serving stack.

Aggregate histograms (`/metrics`) can say p99 TTFT is 800 ms; they
cannot say WHY. This tool ingests the per-request lifecycle records the
server keeps (`serve/reqtrace.py`, exported at ``GET
/v1/requests?full=1``) and answers the operator questions directly:

- **Tail decomposition** - for TTFT and E2E at p50/p95/p99, the share
  of the tail requests' wall-clock per cause: "p99 TTFT = 62%
  queue_wait, 21% kv_alloc_stall, ...". TTFT attribution clips each
  record's spans at its first-token time; E2E uses the whole lifetime.
- **Slow-request exemplars** - the N slowest requests with their full
  span sequences, so one bad request's story is readable end to end.
- **SLO gates** - ``--slo ttft_p99=0.5,e2e_p95=2.0`` checks the
  percentiles and exits shardlint-style: 0 all pass, 1 violations
  (each printed with the dominant cause at that percentile), 2 usage.
- **Client join** (``--client loadgen_requests.jsonl``) - joins
  `tools/loadgen.py --out-requests` rows on the server-echoed
  ``req_id`` and gates the client-observed vs server-attributed E2E
  gap: the honesty rail that catches seconds the server's accounting
  never saw (network, HTTP queueing outside the recorder).
- **Ledger reconciliation** (``--ledger serve_record.json``) - the
  per-request apportioned engine seconds (``engine_s``) summed across
  records must match the serving goodput ledger's prefill / decode /
  kv_alloc_stall buckets within ``max(--ledger-tol x bucket, 0.05 s)``
  (causes that exist on only one side - e.g. the ledger's
  batch_formation_idle, the records' queue_wait - are per-design
  excluded; the records' own span conservation is asserted serverside
  at finalize). Skipped with a warning when records were evicted from
  the server's ring (partial sums cannot reconcile).

Usage:
  python tools/request_trace.py http://127.0.0.1:8000
  python tools/request_trace.py requests.json --slo ttft_p99=0.5
  python tools/request_trace.py requests.json \
      --client loadgen_requests.jsonl --slo e2e_p95=2.0 \
      --ledger serve_record.json

SOURCE is a ``/v1/requests`` JSON dump (file) or a server base URL
(fetched live with ``?full=1``). Stdlib-only - no jax, no repo imports.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import urllib.request

# presentation order (mirrors serve/reqtrace.py REQUEST_CAUSES)
CAUSES = (
    "queue_wait", "admission", "prefill", "decode",
    "kv_alloc_stall", "preempted_wait", "stream_write",
)
LEDGER_CAUSES = ("prefill", "decode", "kv_alloc_stall")
PERCENTILES = (0.50, 0.95, 0.99)
SLO_KEYS = tuple(
    f"{m}_p{int(q * 100)}" for m in ("ttft", "e2e") for q in PERCENTILES
)


def percentile(xs, q: float):
    """Nearest-rank percentile; None when empty."""
    if not xs:
        return None
    s = sorted(xs)
    return s[max(0, math.ceil(q * len(s)) - 1)]


def load_source(source: str) -> dict:
    """A /v1/requests document from a file or a live server URL."""
    if source.startswith(("http://", "https://")):
        url = source.rstrip("/")
        if "/v1/requests" not in url:
            url += "/v1/requests"
        if "?" not in url:
            url += "?full=1"
        with urllib.request.urlopen(url, timeout=30) as r:
            return json.loads(r.read())
    with open(source) as f:
        return json.loads(f.read())


def usable_records(doc: dict) -> list[dict]:
    """Finalized records that carry span sequences (?full=1 dumps)."""
    recent = doc.get("recent") or []
    return [r for r in recent if isinstance(r.get("spans"), list)]


def _metric_value(rec: dict, metric: str):
    return rec.get("ttft_s") if metric == "ttft" else rec.get("e2e_s")


def _clipped_causes(rec: dict, metric: str) -> dict:
    """Per-cause seconds inside the metric's window: [arrival,
    first_token] for ttft, the whole lifetime for e2e."""
    if metric == "ttft":
        hi = rec.get("t_first_token_rel")
        if hi is None:
            return {}
    else:
        hi = float("inf")
    out: dict = {}
    for cause, t0, t1 in rec.get("spans") or ():
        lo, up = float(t0), min(float(t1), hi)
        if up > lo:
            out[cause] = out.get(cause, 0.0) + (up - lo)
    return out


def decompose(records: list[dict], metric: str, q: float):
    """The tail at percentile q: value, size, per-cause shares."""
    vals = [
        (r, v) for r in records
        if (v := _metric_value(r, metric)) is not None
    ]
    if not vals:
        return None
    pv = percentile([v for _, v in vals], q)
    tail = [r for r, v in vals if v >= pv - 1e-12]
    acc: dict = {}
    for r in tail:
        for cause, s in _clipped_causes(r, metric).items():
            acc[cause] = acc.get(cause, 0.0) + s
    total = sum(acc.values())
    shares = (
        {c: acc[c] / total for c in acc} if total > 0 else {}
    )
    dominant = max(shares, key=shares.get) if shares else None
    return {
        "value": pv, "n_tail": len(tail), "n": len(vals),
        "shares": shares, "dominant": dominant,
    }


def _fmt_shares(shares: dict, limit: int = 4) -> str:
    parts = sorted(shares.items(), key=lambda kv: -kv[1])
    out = ", ".join(f"{s * 100:.0f}% {c}" for c, s in parts[:limit])
    if len(parts) > limit:
        out += ", ..."
    return out


def print_report(records: list[dict], doc: dict, n_exemplars: int) -> dict:
    """The decomposition tables + exemplars; returns {slo_key: info}."""
    counts = doc.get("counts") or {}
    print(
        f"Request-trace attribution: {len(records)} finalized record(s) "
        f"with spans (server totals: {counts.get('finalized', '?')} "
        f"finalized, {counts.get('in_flight', '?')} in flight, "
        f"evicted {counts.get('evicted', 0)})"
    )
    prop = sum(r.get("proposed_tokens") or 0 for r in records)
    if prop:
        acc = sum(r.get("accepted_tokens") or 0 for r in records)
        drf = sum(r.get("draft_s") or 0.0 for r in records)
        ver = sum(r.get("verify_s") or 0.0 for r in records)
        print(
            f"Speculative decode: accepted {acc}/{prop} draft tokens "
            f"({100.0 * acc / prop:.1f}%); draft {drf:.4f}s + verify "
            f"{ver:.4f}s device time inside decode"
        )
    # fleet failover provenance (serve/reqtrace.py router_retry): how
    # many of this replica's requests arrived as re-dispatches, plus
    # sequences this replica migrated OUT during a drain
    retried = [
        r for r in records
        if (r.get("router_retry") or {}).get("episodes")
    ]
    migrated = ((counts.get("by_state") or {}).get("migrated", 0))
    if retried or migrated:
        eps = sum(r["router_retry"]["episodes"] for r in retried)
        lost = sum(
            r["router_retry"].get("seconds") or 0.0 for r in retried
        )
        print(
            f"Failover: {len(retried)} request(s) arrived re-dispatched "
            f"({eps} episode(s), {lost:.4f}s lost to retries); "
            f"{migrated} migrated out by drain"
        )
    gates: dict = {}
    for metric, label in (("ttft", "TTFT"), ("e2e", "E2E")):
        for q in PERCENTILES:
            d = decompose(records, metric, q)
            key = f"{metric}_p{int(q * 100)}"
            gates[key] = d
            if d is None:
                print(f"{label:<5} p{int(q * 100):<3} n/a (no samples)")
                continue
            print(
                f"{label:<5} p{int(q * 100):<3} {d['value']:8.4f}s "
                f"({d['n_tail']}/{d['n']} in tail) = "
                f"{_fmt_shares(d['shares'])}"
            )
    ranked = sorted(
        (r for r in records if r.get("e2e_s") is not None),
        key=lambda r: -r["e2e_s"],
    )[:n_exemplars]
    if ranked:
        print(f"Slowest {len(ranked)} request(s) by E2E:")
    for r in ranked:
        ttft = r.get("ttft_s")
        # speculative-decoding acceptance, when the server ran with it:
        # accepted/proposed draft tokens inside this request's decode
        prop = r.get("proposed_tokens") or 0
        acc_note = (
            f" accept={r.get('accepted_tokens', 0)}/{prop}"
            f" ({100.0 * r.get('accepted_tokens', 0) / prop:.0f}%)"
            if prop else ""
        )
        print(
            f"  #{r.get('req_id')} tenant={r.get('tenant')} "
            f"{r.get('state')} e2e={r['e2e_s']:.4f}s "
            f"ttft={'n/a' if ttft is None else f'{ttft:.4f}s'} "
            f"tokens={r.get('tokens_emitted')} "
            f"preempts={r.get('preemptions', 0)}"
            + acc_note
        )
        segs = [
            f"{c} {t1 - t0:.4f}s" for c, t0, t1 in (r.get("spans") or ())
        ]
        shown = segs[:12]
        tail_note = (
            f" -> ... (+{len(segs) - 12} more)" if len(segs) > 12 else ""
        )
        print("      " + " -> ".join(shown) + tail_note)
    return gates


def parse_slo(spec: str) -> dict:
    """``ttft_p99=0.5,e2e_p95=2.0`` -> {key: seconds}. ValueError on
    unknown keys / bad numbers."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key not in SLO_KEYS:
            raise ValueError(
                f"unknown SLO key {key!r} (choose from {SLO_KEYS})"
            )
        try:
            out[key] = float(val)
        except ValueError:
            raise ValueError(f"bad SLO threshold {val!r} for {key}")
        if out[key] <= 0:
            raise ValueError(f"SLO threshold for {key} must be > 0")
    if not out:
        raise ValueError("empty --slo spec")
    return out


def gate_slo(gates: dict, slo: dict) -> list[str]:
    problems = []
    for key, limit in sorted(slo.items()):
        d = gates.get(key)
        if d is None:
            problems.append(f"{key}: no samples to evaluate the SLO")
            continue
        if d["value"] > limit:
            dom = d["dominant"] or "unattributed"
            problems.append(
                f"{key}: {d['value']:.4f}s > SLO {limit:.4f}s - "
                f"dominant cause {dom} "
                f"({_fmt_shares(d['shares'])})"
            )
        else:
            print(f"SLO ok: {key} {d['value']:.4f}s <= {limit:.4f}s")
    return problems


def load_client(path: str) -> list[dict]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def gate_client(records: list[dict], rows: list[dict],
                gap_tol: float, slack: float) -> list[str]:
    """Join client rows on req_id; gate client-vs-server E2E gap."""
    problems = []
    by_id = {
        r["req_id"]: r for r in records
        if isinstance(r.get("req_id"), int)
    }
    completed = [
        c for c in rows
        if c.get("status") == "completed"
        and isinstance(c.get("req_id"), int)
        and c.get("e2e_s") is not None
    ]
    joined = []
    for c in completed:
        s = by_id.get(c["req_id"])
        if s is not None and s.get("state") == "done" \
                and s.get("e2e_s") is not None:
            joined.append((c, s))
    if completed and not joined:
        return [
            f"client join matched 0 of {len(completed)} completed "
            "client rows (req_id echo broken, or the server ring "
            "evicted them)"
        ]
    if not completed:
        return ["client file has no completed rows with req_id to join"]
    gaps = [c["e2e_s"] - s["e2e_s"] for c, s in joined]
    p50 = percentile(gaps, 0.50)
    p95 = percentile(gaps, 0.95)
    worst_neg = min(gaps)
    print(
        f"Client join: {len(joined)}/{len(completed)} completed "
        f"request(s) matched; client-vs-server E2E gap p50 "
        f"{p50 * 1e3:.1f} ms, p95 {p95 * 1e3:.1f} ms, "
        f"min {worst_neg * 1e3:.1f} ms"
    )
    if worst_neg < -slack:
        problems.append(
            f"client gap: server attributed {-worst_neg:.4f}s MORE than "
            f"the client observed (> {slack:.3f}s slack) - the "
            "accounting claims time that did not happen"
        )
    if p95 > gap_tol:
        problems.append(
            f"client gap: p95 {p95:.4f}s > tolerance {gap_tol:.4f}s - "
            "the server's attribution misses too much client-visible "
            "latency"
        )
    return problems


def gate_ledger(records: list[dict], doc: dict, ledger_path: str,
                rel_tol: float) -> list[str]:
    """Sum per-record engine_s and reconcile vs the serve goodput
    record's prefill/decode/kv_alloc_stall buckets."""
    with open(ledger_path) as f:
        rec = json.loads(f.read())
    if rec.get("taxonomy") != "serve":
        return [
            f"--ledger: {ledger_path} has taxonomy "
            f"{rec.get('taxonomy')!r}, need the serving record"
        ]
    evicted = (doc.get("counts") or {}).get("evicted", 0)
    if evicted:
        print(
            f"WARNING: ledger reconciliation skipped - {evicted} "
            "record(s) evicted from the server ring, per-request sums "
            "are partial (raise --request-ring)"
        )
        return []
    badput = rec.get("badput_s") or {}
    ledger_vals = {
        "decode": rec.get("goodput_s") or 0.0,
        "prefill": badput.get("prefill") or 0.0,
        "kv_alloc_stall": badput.get("kv_alloc_stall") or 0.0,
    }
    problems = []
    for cause in LEDGER_CAUSES:
        mine = sum(
            (r.get("engine_s") or {}).get(cause, 0.0) for r in records
        )
        theirs = ledger_vals[cause]
        tol = max(rel_tol * max(theirs, mine), 0.05)
        line = (
            f"ledger {cause}: requests {mine:.4f}s vs ledger "
            f"{theirs:.4f}s (tol {tol:.4f}s)"
        )
        if abs(mine - theirs) > tol:
            problems.append(line + " - RECONCILIATION FAILED")
        else:
            print(line + " - ok")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "source",
        help="a /v1/requests?full=1 JSON dump, or the server base URL",
    )
    ap.add_argument(
        "--slo", default=None,
        help="comma list of gates, e.g. ttft_p99=0.5,e2e_p95=2.0 "
        f"(keys: {', '.join(SLO_KEYS)})",
    )
    ap.add_argument(
        "--client", default=None,
        help="tools/loadgen.py --out-requests JSONL to join on req_id",
    )
    ap.add_argument(
        "--client-gap-tol", type=float, default=0.75,
        help="max allowed p95 client-vs-server E2E gap, seconds "
        "(default 0.75)",
    )
    ap.add_argument(
        "--client-slack", type=float, default=0.05,
        help="allowed negative gap (server > client), seconds "
        "(default 0.05)",
    )
    ap.add_argument(
        "--ledger", default=None,
        help="serving goodput record (--run-record output) to "
        "reconcile per-request engine seconds against",
    )
    ap.add_argument(
        "--ledger-tol", type=float, default=0.05,
        help="relative reconciliation tolerance per cause; the gate is "
        "max(tol x bucket, 0.05 s) (default 0.05)",
    )
    ap.add_argument(
        "--exemplars", type=int, default=3,
        help="slowest-request span sequences to print (default 3)",
    )
    args = ap.parse_args(argv)

    slo = None
    if args.slo:
        try:
            slo = parse_slo(args.slo)
        except ValueError as e:
            print(f"request_trace: {e}", file=sys.stderr)
            return 2
    try:
        doc = load_source(args.source)
    except (OSError, ValueError) as e:
        print(f"request_trace: cannot load {args.source}: {e}",
              file=sys.stderr)
        return 2
    records = usable_records(doc)
    if not records:
        print(
            "request_trace: no finalized records with spans in the "
            "source (fetch /v1/requests?full=1, and send traffic first)",
            file=sys.stderr,
        )
        return 2

    gates = print_report(records, doc, max(args.exemplars, 0))
    problems = []
    if slo:
        problems += gate_slo(gates, slo)
    if args.client:
        try:
            rows = load_client(args.client)
        except (OSError, ValueError) as e:
            print(f"request_trace: cannot load --client: {e}",
                  file=sys.stderr)
            return 2
        problems += gate_client(
            records, rows, args.client_gap_tol, args.client_slack
        )
    if args.ledger:
        try:
            problems += gate_ledger(
                records, doc, args.ledger, args.ledger_tol
            )
        except (OSError, ValueError) as e:
            print(f"request_trace: cannot load --ledger: {e}",
                  file=sys.stderr)
            return 2

    if problems:
        print("REQUEST_TRACE GATE FAILED:", file=sys.stderr)
        for prob in problems:
            print(f"  - {prob}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
