#!/usr/bin/env python
"""live_top: a terminal dashboard for a LIVE training run.

Renders either a live `/metrics` endpoint (a run started with
`--metrics-port`, docs/OBSERVABILITY.md "Live monitoring") or a tailing
metrics JSONL file (`--metrics-jsonl` - works on runs without the HTTP
server, and on dead runs' files). One compact ANSI frame per refresh:

  - header: step, readiness (compiling vs training), heartbeat age,
    uptime - the same facts `/healthz` reports;
  - loss sparkline over the recent window + last value;
  - throughput, step-time p50/p95 (from the train_step_seconds histogram
    buckets), device memory, collective bytes;
  - guard anomaly / rollback counters and watchdog flags (stall,
    recompile storm, stale checkpoint) - red when non-zero;
  - model health (a run started with --dynamics, train/dynamics.py):
    gradient/param norms + sparkline, update-to-weight ratio, the
    gradient-noise-scale readout, the guard's live loss z-score, the
    hottest layer by gradient norm, non-finite row count (red), and
    replica divergence at the last parameter sync;
  - when pointed at a tools/launch.py --metrics-port endpoint: the
    elastic supervisor's group size vs target, worker failures by
    signal, shrink/grow/rendezvous restarts, and restart latency -
    plus the FLEET view (train/supervisor.py FleetFederation): one row
    per rank (step, step time, loss, up/DOWN), the attributed straggler
    rank, a step-skew sparkline, and restart/postmortem counters;
  - when pointed at a serving endpoint (python -m
    distributed_neural_network_tpu.serve): QPS (from completed-request
    counter deltas), TTFT p50/p99 + sparkline, inter-token p99,
    active/queued sequences, and KV-block utilization color-banded by
    occupancy (green < 70% < yellow < 90% < red).

Stdlib-only (no jax, no repo imports) so it runs anywhere - including a
laptop pointed at a forwarded TPU host port.

Usage:
  python tools/live_top.py http://127.0.0.1:9090        # live endpoint
  python tools/live_top.py runs/lm.jsonl                # tail a JSONL
  python tools/live_top.py http://host:9090 --once      # one frame (CI)
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
import urllib.error
import urllib.request

SPARK = "▁▂▃▄▅▆▇█"  # ▁▂▃▄▅▆▇█
RED, GREEN, YELLOW, DIM, BOLD, RESET = (
    "\x1b[31m", "\x1b[32m", "\x1b[33m", "\x1b[2m", "\x1b[1m", "\x1b[0m"
)


# ------------------------------------------------------ Prometheus parsing


def parse_prometheus(text: str) -> dict:
    """{metric_name: {labels_frozenset_as_sorted_tuple: float}} from
    Prometheus text exposition. Histogram series keep their _bucket/_sum/
    _count suffixes as distinct metric names."""
    out: dict[str, dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labels_s, value_s = rest.rsplit("}", 1)
                labels = []
                for part in _split_labels(labels_s):
                    k, v = part.split("=", 1)
                    labels.append((k, _unescape(v.strip('"'))))
                key = tuple(sorted(labels))
            else:
                name, value_s = line.rsplit(None, 1)
                key = ()
            v = value_s.strip()
            value = float("inf") if v == "+Inf" else (
                float("-inf") if v == "-Inf" else float(v)
            )
        except ValueError:
            continue  # malformed line: skip, never crash a dashboard
        out.setdefault(name.strip(), {})[key] = value
    return out


def _unescape(s: str) -> str:
    """Reverse the exposition-format label escaping (\\\\, \\", \\n)."""
    return (
        s.replace("\\\\", "\0")
        .replace('\\"', '"')
        .replace("\\n", "\n")
        .replace("\0", "\\")
    )


def _split_labels(s: str):
    """Split 'a="x",b="y,z"' on commas outside quotes."""
    parts, buf, in_q, esc = [], [], False, False
    for ch in s:
        if esc:
            buf.append(ch)
            esc = False
            continue
        if ch == "\\":
            buf.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
            buf.append(ch)
            continue
        if ch == "," and not in_q:
            parts.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return [p for p in (p.strip() for p in parts) if p]


def metric_value(metrics: dict, name: str, default=None):
    fam = metrics.get(name)
    if not fam:
        return default
    if () in fam:
        return fam[()]
    return next(iter(fam.values()))


def labeled_value(metrics: dict, name: str, default=None, **labels):
    """The sample of ``name`` whose label set contains ``labels``."""
    fam = metrics.get(name)
    if not fam:
        return default
    want = set(labels.items())
    for key, v in fam.items():
        if want <= set(key):
            return v
    return default


def metric_sum(metrics: dict, name: str) -> float:
    return sum((metrics.get(name) or {}).values())


def hist_quantile(metrics: dict, name: str, q: float):
    """Approximate quantile from <name>_bucket cumulative counts (upper
    bucket bound containing the q-th observation)."""
    fam = metrics.get(name + "_bucket") or {}
    buckets = []
    for key, cum in fam.items():
        le = dict(key).get("le")
        if le is None:
            continue
        bound = math.inf if le == "+Inf" else float(le)
        buckets.append((bound, cum))
    if not buckets:
        return None
    buckets.sort()
    total = buckets[-1][1]
    if total <= 0:
        return None
    target = q * total
    prev_bound = None
    for bound, cum in buckets:
        if cum >= target:
            return bound if not math.isinf(bound) else prev_bound
        prev_bound = bound
    return prev_bound


# ----------------------------------------------------------- data sources


class EndpointSource:
    """Polls /metrics (+ /healthz) of a live run."""

    def __init__(self, base_url: str, timeout: float = 3.0):
        self.base = base_url.rstrip("/")
        if self.base.endswith("/metrics"):
            self.base = self.base[: -len("/metrics")]
        self.timeout = timeout
        self.loss_history: list[float] = []
        self.grad_history: list[float] = []
        self.skew_history: list[float] = []
        self.qps_history: list[float] = []
        self.ttft_history: list[float] = []
        self._last_completed: tuple | None = None  # (t, count)
        self.error: str | None = None

    def _get(self, path: str) -> str | None:
        try:
            with urllib.request.urlopen(
                self.base + path, timeout=self.timeout
            ) as r:
                body = r.read().decode()
            self.error = None
            return body
        except (urllib.error.URLError, OSError, ValueError) as e:
            # /healthz answers 503 when stalled - that still carries a body
            if isinstance(e, urllib.error.HTTPError):
                try:
                    return e.read().decode()
                except Exception:
                    pass
            self.error = f"{type(e).__name__}: {e}"
            return None

    def sample(self) -> dict | None:
        body = self._get("/metrics")
        if body is None:
            return None
        metrics = parse_prometheus(body)
        health = None
        hz = self._get("/healthz")
        if hz:
            try:
                health = json.loads(hz)
            except ValueError:
                pass
        loss = metric_value(metrics, "train_loss")
        if loss is not None and math.isfinite(loss):
            if not self.loss_history or self.loss_history[-1] != loss:
                self.loss_history.append(loss)
                del self.loss_history[:-512]
        gn = metric_value(metrics, "dynamics_grad_norm")
        if gn is not None and math.isfinite(gn):
            if not self.grad_history or self.grad_history[-1] != gn:
                self.grad_history.append(gn)
                del self.grad_history[:-512]
        skew = metric_value(metrics, "fleet_last_step_skew_seconds")
        if skew is not None and math.isfinite(skew):
            self.skew_history.append(skew)
            del self.skew_history[:-512]
        # serving view histories (serve/scheduler.py series): QPS from
        # completed-request counter deltas, TTFT p50 per sample
        completed = labeled_value(
            metrics, "serve_requests_total", status="completed"
        )
        if completed is None:
            # fleet router endpoint (serve/fleet.py): same QPS series
            # from the router-side completed counter
            completed = labeled_value(
                metrics, "fleet_router_requests_total", status="completed"
            )
        if completed is not None:
            now = time.time()
            if self._last_completed is not None:
                dt = now - self._last_completed[0]
                if dt > 0:
                    self.qps_history.append(
                        max(0.0, (completed - self._last_completed[1]) / dt)
                    )
                    del self.qps_history[:-512]
            self._last_completed = (now, completed)
        ttft = hist_quantile(metrics, "serve_ttft_seconds", 0.50)
        if ttft is not None and math.isfinite(ttft):
            self.ttft_history.append(ttft)
            del self.ttft_history[:-512]
        # per-request lifecycle records (serve endpoints only); a miss
        # must not clobber the good /metrics sample's error state
        requests = None
        if "serve_requests_total" in metrics:
            err = self.error
            body_rq = self._get("/v1/requests")
            self.error = err
            if body_rq:
                try:
                    requests = json.loads(body_rq)
                except ValueError:
                    pass
        # fleet-router targets (serve/fleet.py): per-replica detail +
        # autoscaler target vs actual from GET /v1/fleet
        fleet = None
        if "fleet_router_requests_total" in metrics \
                or "fleet_replicas" in metrics:
            err = self.error
            body_fl = self._get("/v1/fleet")
            self.error = err
            if body_fl:
                try:
                    fleet = json.loads(body_fl)
                except ValueError:
                    pass
        return {"metrics": metrics, "health": health, "fleet": fleet,
                "loss_history": list(self.loss_history),
                "grad_history": list(self.grad_history),
                "skew_history": list(self.skew_history),
                "qps_history": list(self.qps_history),
                "ttft_history": list(self.ttft_history),
                "requests": requests,
                "source": self.base}


class JsonlSource:
    """Tails a metrics JSONL file (utils/metrics.py JsonlSink schema:
    {"t":..., "series":..., "value":...} per line); malformed lines are
    skipped. Builds the same snapshot shape the endpoint source yields,
    from the series the sinks actually stream (train/loss, step/*)."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self.series: dict[str, list[float]] = {}
        self.last_t: float | None = None

    def sample(self) -> dict | None:
        try:
            with open(self.path) as f:
                f.seek(self._pos)
                chunk = f.read()
                self._pos = f.tell()
        except OSError:
            return None
        for line in chunk.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if not isinstance(ev, dict):
                continue
            s, v = ev.get("series"), ev.get("value")
            if isinstance(s, str) and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                self.series.setdefault(s, []).append(float(v))
                del self.series[s][:-512]
                if isinstance(ev.get("t"), (int, float)):
                    self.last_t = float(ev["t"])
        loss_hist = (
            self.series.get("train/loss")
            or self.series.get("step/loss") or []
        )
        metrics: dict = {}
        walls = self.series.get("step/wall_s") or []
        if walls:
            metrics["train_step_last_s"] = {(): walls[-1]}
            metrics["train_steps_total"] = {(): float(len(walls))}
        for thr_key in ("step/tokens_per_s", "step/images_per_s"):
            if self.series.get(thr_key):
                metrics["train_throughput_items_per_s"] = {
                    (): self.series[thr_key][-1]
                }
        if self.series.get("step/mem_bytes_in_use_max"):
            metrics["device_memory_bytes_in_use"] = {
                (("device", "max"),):
                    self.series["step/mem_bytes_in_use_max"][-1]
            }
        # the engine's replica-divergence series (train/engine.py run())
        # surface as the same gauges the endpoint source would see
        for s_key, gname in (
            ("dynamics/replica_div_mean", "dynamics_replica_div_mean"),
            ("dynamics/replica_div_max", "dynamics_replica_div_max"),
        ):
            if self.series.get(s_key):
                metrics[gname] = {(): self.series[s_key][-1]}
        for s, vals in self.series.items():
            if s.startswith("step/anomaly_"):
                metrics.setdefault("guard_anomalies_total", {})[
                    (("kind", s[len("step/anomaly_"):]),)
                ] = vals[-1]
        if loss_hist:
            metrics["train_loss"] = {(): loss_hist[-1]}
        health = None
        if self.last_t is not None:
            age = max(0.0, time.time() - self.last_t)
            health = {"alive": True, "ready": bool(walls or loss_hist),
                      "heartbeat_age_s": round(age, 3), "step": None,
                      "uptime_s": None}
        return {"metrics": metrics, "health": health,
                "loss_history": list(loss_hist), "source": self.path,
                "file_mode": True}


# -------------------------------------------------------------- rendering


def sparkline(xs, width: int = 48) -> str:
    if not xs:
        return ""
    xs = xs[-width:]
    lo, hi = min(xs), max(xs)
    if not (math.isfinite(lo) and math.isfinite(hi)):
        return "(non-finite)"
    span = hi - lo
    if span <= 0:
        return SPARK[0] * len(xs)
    return "".join(
        SPARK[min(len(SPARK) - 1, int((x - lo) / span * len(SPARK)))]
        for x in xs
    )


def fmt_bytes(b) -> str:
    if b is None:
        return "n/a"
    b = float(b)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if b < 1024 or unit == "TiB":
            return f"{b:,.1f} {unit}" if unit != "B" else f"{int(b)} B"
        b /= 1024
    return f"{b:.1f} TiB"


def fmt_rate(v) -> str:
    if v is None:
        return "n/a"
    return f"{v:,.0f}/s"


def render(snap: dict, *, color: bool = True, width: int = 72) -> str:
    """One dashboard frame as a string (ANSI colors optional)."""
    c = (lambda code, s: f"{code}{s}{RESET}") if color else (lambda _c, s: s)
    m = snap["metrics"]
    health = snap.get("health") or {}
    lines = []
    steps = metric_value(m, "train_steps_total")
    ready = health.get("ready")
    if ready is None:
        ready = bool(metric_value(m, "train_ready", 0))
    age = health.get("heartbeat_age_s")
    state = (
        c(GREEN, "training") if ready
        else c(YELLOW, "compiling/starting")
    )
    alive = health.get("alive", True)
    if not alive:
        state = c(RED, "STALLED")
    head = (
        f"{c(BOLD, 'live_top')}  {snap['source']}  [{state}]  "
        f"step {int(steps) if steps is not None else '?'}"
    )
    if age is not None:
        head += f"  heartbeat {age:.1f}s ago"
    lines.append(head)
    lines.append(c(DIM, "-" * width))
    # loss
    hist = snap.get("loss_history") or []
    loss = metric_value(m, "train_loss")
    lines.append(
        "loss        "
        + (f"{loss:.5g}  " if loss is not None else "n/a      ")
        + sparkline(hist, width - 24)
    )
    # throughput + step time
    thr = metric_value(m, "train_throughput_items_per_s")
    p50 = hist_quantile(m, "train_step_seconds", 0.50)
    p95 = hist_quantile(m, "train_step_seconds", 0.95)
    if p50 is None and metric_value(m, "train_step_last_s") is not None:
        step_s = f"last<= {metric_value(m, 'train_step_last_s'):.4g}s"
    elif p50 is not None:
        step_s = f"p50<={p50:.4g}s p95<={p95:.4g}s"
    else:
        step_s = "n/a"
    lines.append(f"throughput  {fmt_rate(thr)}   step time {step_s}")
    # memory + collectives
    mem = m.get("device_memory_bytes_in_use") or {}
    mem_s = (
        fmt_bytes(max(mem.values())) + f" peak x{len(mem)} dev"
        if mem else "n/a"
    )
    comm = metric_value(m, "collective_bytes_per_step")
    lines.append(
        f"memory      {mem_s}   collective "
        + (fmt_bytes(comm) + "/step" if comm is not None else "n/a")
    )
    # checkpoint
    last_save = metric_value(m, "checkpoint_last_save_timestamp_seconds")
    if last_save:
        ck_age = max(0.0, time.time() - last_save)
        saves = metric_value(m, "checkpoint_saves_total", 0)
        lines.append(
            f"checkpoint  {int(saves)} saved, newest {ck_age:,.0f}s ago "
            f"(step {int(metric_value(m, 'checkpoint_last_step', -1))})"
        )
    # guard + watchdog
    anomalies = m.get("guard_anomalies_total") or {}
    anom_s = ", ".join(
        f"{dict(k).get('kind', '?')}={int(v)}"
        for k, v in sorted(anomalies.items())
    ) or "none"
    rb = metric_value(m, "guard_rollbacks_total", 0)
    guard_line = f"guard       anomalies: {anom_s}  rollbacks: {int(rb)}"
    if anomalies or rb:
        guard_line = c(YELLOW, guard_line)
    lines.append(guard_line)
    stall = metric_value(m, "watchdog_stall_total", 0)
    storm = metric_value(m, "watchdog_recompile_storm_total", 0)
    stale = metric_value(m, "watchdog_checkpoint_stale_total", 0)
    rec = metric_value(m, "recompiles_total", 0)
    dog = (
        f"watchdog    stalls: {int(stall)}  recompiles: {int(rec)}"
        f"  storms: {int(storm)}  stale-ckpt: {int(stale)}"
    )
    if stall or storm or stale:
        dog = c(RED, dog)
    lines.append(dog)
    # model health (train/dynamics.py; present when the run was started
    # with --dynamics): the gauges the DynamicsSink / engine publish
    gn = metric_value(m, "dynamics_grad_norm")
    div_mean = metric_value(m, "dynamics_replica_div_mean")
    if gn is not None or div_mean is not None:
        parts = []
        if gn is not None:
            parts.append(f"|g| {gn:.4g}")
        pn = metric_value(m, "dynamics_param_norm")
        if pn is not None:
            parts.append(f"|w| {pn:.4g}")
        upd = metric_value(m, "dynamics_upd_ratio_max")
        if upd is not None:
            parts.append(f"upd/w max {upd:.3g}")
        z = metric_value(m, "guard_spike_zscore")
        if z is not None:
            parts.append(f"loss z {z:+.2f}")
        if parts:  # engine runs publish divergence only: no empty line
            model_line = "model       " + "  ".join(parts)
            nonfin = metric_value(m, "dynamics_nonfinite_rows_total", 0)
            if nonfin:
                model_line += c(RED, f"  NON-FINITE rows: {int(nonfin)}")
            spark = sparkline(snap.get("grad_history") or [], 16)
            if spark:
                model_line += f"  {spark}"
            lines.append(model_line)
        gns_v = metric_value(m, "dynamics_gns_noise_scale")
        if gns_v is not None:
            crit = metric_value(m, "dynamics_crit_batch_size")
            lines.append(
                f"  gns noise_scale {gns_v:.4g}"
                + (f"  crit batch {crit:,.0f} tokens"
                   if crit is not None else "")
            )
        layer_fam = m.get("dynamics_layer_grad_norm") or {}
        if layer_fam:
            hot_key, hot_v = max(layer_fam.items(), key=lambda kv: kv[1])
            lines.append(
                f"  hottest layer {dict(hot_key).get('layer', '?')}  "
                f"|g| {hot_v:.4g}"
            )
        if div_mean is not None:
            div_max = metric_value(m, "dynamics_replica_div_max")
            lines.append(
                f"  replica divergence mean {div_mean:.4g}"
                + (f"  max {div_max:.4g}" if div_max is not None else "")
            )
    # goodput accounting (utils/goodput.py; published by a worker's own
    # ledger or the supervisor's fleet aggregation): what fraction of
    # wall-clock produced training progress, and where the rest went
    gp = metric_value(m, "goodput_ratio")
    predicted = snap.get("predicted") or {}
    pred_ratio = predicted.get("ratio")
    if gp is not None:
        badput = m.get("badput_seconds_total") or {}
        top = sorted(
            ((dict(k).get("cause", "?"), v) for k, v in badput.items()
             if v > 0),
            key=lambda kv: -kv[1],
        )[:4]
        gp_line = f"goodput     {100.0 * gp:5.1f}%"
        if top:
            gp_line += "  badput: " + "  ".join(
                f"{cause}={v:.1f}s" for cause, v in top
            )
        # color by ratio: the fleet's headline number reads at a glance
        gp_line = c(GREEN if gp >= 0.8 else YELLOW if gp >= 0.5 else RED,
                    gp_line)
        if pred_ratio is not None:
            # a fleetsim prediction (tools/fleetsim.py -o fleetsim.json
            # in the run dir): show the predicted-vs-actual gap, color-
            # banded by |gap| - a run drifting from its digital twin is
            # the signal to re-extract distributions or suspect the run
            gap = gp - pred_ratio
            gap_col = (
                GREEN if abs(gap) < 0.05
                else YELLOW if abs(gap) < 0.15 else RED
            )
            gp_line += c(
                gap_col,
                f"  predicted {100.0 * pred_ratio:5.1f}% "
                f"(gap {100.0 * gap:+.1f}%)",
            )
        lines.append(gp_line)
    elif pred_ratio is not None:
        lines.append(c(
            DIM,
            f"goodput     n/a  predicted {100.0 * pred_ratio:5.1f}% "
            "(fleetsim; no measured ratio yet)",
        ))
    # elastic supervisor (train/supervisor.py; present when the target is
    # a tools/launch.py --metrics-port endpoint)
    gsz = metric_value(m, "supervisor_group_size")
    if gsz is not None:
        target = metric_value(m, "supervisor_target_size", gsz)
        fails = m.get("worker_failures_total") or {}
        fail_s = ", ".join(
            f"{dict(k).get('signal', '?')}={int(v)}"
            for k, v in sorted(fails.items()) if v
        ) or "none"
        restarts = m.get("elastic_restarts_total") or {}
        rst_s = ", ".join(
            f"{dict(k).get('direction', dict(k).get('kind', '?'))}={int(v)}"
            for k, v in sorted(restarts.items()) if v
        ) or "none"
        p95r = hist_quantile(m, "supervisor_restart_seconds", 0.95)
        budget = metric_value(m, "supervisor_restart_budget_remaining")
        sup_line = (
            f"supervisor  group {int(gsz)}/{int(target)}  "
            f"failures: {fail_s}  restarts: {rst_s}"
            + (f"  restart p95<={p95r:.3g}s" if p95r is not None else "")
            + (f"  budget left: {int(budget)}" if budget is not None else "")
        )
        if sum(fails.values()) or int(gsz) < int(target):
            sup_line = c(YELLOW, sup_line)
        lines.append(sup_line)
    # fleet view (train/supervisor.py FleetFederation): one row per rank
    # plus straggler attribution, the step-skew sparkline, and
    # restart/postmortem counters
    fleet_steps = m.get("fleet_worker_step") or {}
    if fleet_steps:
        straggler = metric_value(m, "fleet_straggler_rank")
        skew_last = metric_value(m, "fleet_last_step_skew_seconds")
        pm = metric_value(m, "supervisor_postmortems_total", 0)
        restarts = metric_sum(m, "elastic_restarts_total")
        head = "fleet       straggler: " + (
            f"rank {int(straggler)}"
            if straggler is not None and straggler >= 0 else "none"
        )
        if skew_last is not None:
            head += f"  skew {skew_last:.3g}s"
        spark = sparkline(snap.get("skew_history") or [], 16)
        if spark:
            head += f"  {spark}"
        head += f"  restarts: {int(restarts)}  postmortems: {int(pm)}"
        if (straggler is not None and straggler >= 0) or pm:
            head = c(YELLOW, head)
        lines.append(head)
        for key in sorted(
            fleet_steps, key=lambda k: int(dict(k).get("rank", -1))
        ):
            r = dict(key).get("rank", "?")
            step_s = labeled_value(
                m, "fleet_worker_step_seconds", rank=r
            )
            loss_r = labeled_value(m, "fleet_train_loss", rank=r)
            up = labeled_value(m, "fleet_worker_up", 0, rank=r)
            row = (
                f"  rank {r:<3} step {int(fleet_steps[key]):>6}  "
                + (f"{step_s:.3g}s/step  " if step_s is not None else "")
                + (f"loss {loss_r:.5g}  " if loss_r is not None else "")
            )
            row += c(GREEN, "up") if up else c(RED, "DOWN")
            if straggler is not None and str(int(straggler)) == str(r):
                row = c(YELLOW, row)
            lines.append(row)
    # serving view (serve/scheduler.py): QPS, TTFT percentiles +
    # sparkline, active/queued sequences, KV-block utilization
    # color-banded by occupancy - present when the target is a
    # `python -m distributed_neural_network_tpu.serve` endpoint
    served = m.get("serve_requests_total") or {}
    if served:
        completed = labeled_value(
            m, "serve_requests_total", 0, status="completed"
        )
        accepted = labeled_value(
            m, "serve_requests_total", 0, status="accepted"
        )
        rejected = metric_sum(m, "serve_rejected_total")
        qps_hist = snap.get("qps_history") or []
        qps = qps_hist[-1] if qps_hist else None
        line = (
            "serving     "
            + (f"{qps:.2f} req/s  " if qps is not None else "")
            + f"completed {int(completed)}/{int(accepted)} accepted"
            + (
                c(YELLOW, f"  429s {int(rejected)}")
                if rejected else "  429s 0"
            )
        )
        lines.append(line)
        ttft50 = hist_quantile(m, "serve_ttft_seconds", 0.50)
        ttft99 = hist_quantile(m, "serve_ttft_seconds", 0.99)
        it99 = hist_quantile(m, "serve_intertoken_seconds", 0.99)
        ttft_s = (
            f"ttft p50<={ttft50:.3g}s p99<={ttft99:.3g}s"
            if ttft50 is not None else "ttft n/a"
        )
        spark = sparkline(snap.get("ttft_history") or [], 20)
        lines.append(
            "  " + ttft_s
            + (f"  inter-token p99<={it99:.3g}s" if it99 is not None else "")
            + (f"  {spark}" if spark else "")
        )
        # serve digital twin (tools/fleetsim.py --serve -o
        # fleetsim_serve.json in the run dir): predicted-vs-actual TTFT
        # p99 and goodput-ratio gap, color-banded like the training gap
        # line - a server drifting from its twin means the distributions
        # are stale or the run is sick
        pred_serve = snap.get("predicted_serve") or {}
        if pred_serve:
            parts = []
            pv = pred_serve.get("ttft_p99")
            if pv is not None and ttft99 is not None and pv > 0:
                rel = (ttft99 - pv) / pv
                col = (
                    GREEN if abs(rel) < 0.05
                    else YELLOW if abs(rel) < 0.15 else RED
                )
                parts.append(c(
                    col,
                    f"ttft p99 predicted {pv:.3g}s (gap {100.0 * rel:+.0f}%)"
                ))
            pr = pred_serve.get("ratio")
            if pr is not None and gp is not None:
                sgap = gp - pr
                col = (
                    GREEN if abs(sgap) < 0.05
                    else YELLOW if abs(sgap) < 0.15 else RED
                )
                parts.append(c(
                    col,
                    f"goodput predicted {100.0 * pr:5.1f}% "
                    f"(gap {100.0 * sgap:+.1f}pp)"
                ))
            if parts:
                lines.append("  twin: " + "  ".join(parts))
        active = metric_value(m, "serve_active_sequences", 0)
        queued = metric_value(m, "serve_queue_depth", 0)
        kv_used = metric_value(m, "serve_kv_blocks_in_use", 0)
        kv_total = metric_value(m, "serve_kv_blocks_total", 0)
        preempt = metric_value(m, "serve_preemptions_total", 0)
        util = kv_used / kv_total if kv_total else 0.0
        kv_col = GREEN if util < 0.7 else YELLOW if util < 0.9 else RED
        # quantized-byte accounting (serve/scheduler.py): occupancy in
        # the bytes the pool dtype actually allocates + the effective
        # concurrent-sequence capacity, so an int8-KV server reads as
        # the capacity it really has rather than a raw block count
        kv_dtype = next(
            (dict(k).get("dtype") for k, v in
             (m.get("serve_kv_dtype") or {}).items() if v), None
        )
        bytes_used = metric_value(m, "serve_kv_bytes_in_use", 0)
        bytes_total = metric_value(m, "serve_kv_bytes_total", 0)
        capacity = metric_value(m, "serve_kv_capacity_sequences", None)
        byte_s = (
            f" {fmt_bytes(bytes_used)}/{fmt_bytes(bytes_total)}"
            + (f" {kv_dtype}" if kv_dtype else "")
            if bytes_total else ""
        )
        kv_line = (
            f"  active {int(active)}  queued {int(queued)}  "
            + c(kv_col,
                f"kv {int(kv_used)}/{int(kv_total)} blocks "
                f"({100.0 * util:.0f}%){byte_s}")
            + (f"  cap {int(capacity)} seqs"
               if capacity is not None else "")
            + (f"  preempted {int(preempt)}" if preempt else "")
        )
        lines.append(kv_line)
        # speculative decoding (serve/engine.py --spec-decode):
        # accepted/proposed draft tokens color-banded by acceptance rate
        # - below 40% the drafter is wasting more verify work than the
        # accepted tokens buy back
        spec_prop = metric_value(m, "serve_spec_proposed_tokens_total", 0)
        if spec_prop:
            spec_acc = metric_value(
                m, "serve_spec_accepted_tokens_total", 0
            )
            rate = spec_acc / spec_prop
            rate_col = (
                RED if rate < 0.4 else YELLOW if rate < 0.6 else GREEN
            )
            lines.append(
                "  spec-decode "
                + c(rate_col,
                    f"accept {int(spec_acc)}/{int(spec_prop)} "
                    f"({100.0 * rate:.0f}%)")
            )
        # slowest in-flight requests (GET /v1/requests, serve/reqtrace):
        # age + current state + dominant lifecycle cause per request -
        # the tail drill-down an aggregate histogram cannot give
        inflight = (snap.get("requests") or {}).get("in_flight") or []
        if inflight:
            rows = sorted(
                inflight, key=lambda r: -(r.get("age_s") or 0.0)
            )[:4]
            lines.append("  slowest in-flight:")
            for r in rows:
                state = r.get("state", "?")
                age = r.get("age_s")
                pre = r.get("preemptions") or 0
                row = (
                    f"    #{r.get('req_id', '?')} "
                    f"{r.get('tenant', '?')} {state}"
                    + (f" age {age:.2f}s" if age is not None else "")
                    + f" tok {r.get('tokens_emitted', 0)}"
                    + (f" preempt x{pre}" if pre else "")
                    + f" dominant {r.get('dominant_cause', '?')}"
                )
                if state in ("kv_alloc_stall", "preempted_wait"):
                    row = c(RED, row)
                lines.append(row)
    # serving-fleet view (serve/fleet.py router): autoscaler target vs
    # actual, router failover counters, and one row per replica with
    # QPS, TTFT p99, KV occupancy and up/DRAINING/DOWN state - present
    # when the target is a tools/serve_fleet.py router endpoint
    fleet_doc = snap.get("fleet")
    if fleet_doc is None and "fleet_router_requests_total" in m:
        fleet_doc = {}
    if fleet_doc is not None:
        router = fleet_doc.get("router") or {}
        target = fleet_doc.get(
            "target_replicas", metric_value(m, "fleet_target_replicas", 0)
        )
        actual = fleet_doc.get(
            "actual_replicas", metric_value(m, "fleet_actual_replicas", 0)
        )
        completed = router.get(
            "requests_completed",
            labeled_value(
                m, "fleet_router_requests_total", 0, status="completed"
            ),
        )
        retries = router.get(
            "retries_total",
            metric_value(m, "fleet_router_retries_total", 0),
        )
        failures = router.get(
            "replica_failures",
            metric_value(m, "fleet_replica_failures_total", 0),
        )
        tgt_s = f"replicas {int(actual)}/{int(target)} target"
        if int(actual) < int(target):
            tgt_s = c(YELLOW, tgt_s)
        head = (
            f"fleet       {tgt_s}  completed {int(completed)}"
            + (
                c(YELLOW, f"  failover retries {int(retries)}")
                if retries else "  failover retries 0"
            )
            + (
                c(RED, f"  replica failures {int(failures)}")
                if failures else ""
            )
        )
        lines.append(head)
        qps_hist = snap.get("qps_history") or []
        fleet_qps = qps_hist[-1] if qps_hist else None
        for rep in fleet_doc.get("replicas") or []:
            rid = rep.get("replica", "?")
            state = rep.get("state", "?")
            state_s = {
                "up": c(GREEN, "up"),
                "draining": c(YELLOW, "DRAINING"),
            }.get(state, c(RED, state.upper()))
            kv_used = rep.get("kv_blocks_in_use") or 0
            kv_total = rep.get("kv_blocks_total") or 0
            util = rep.get("kv_utilization") or (
                kv_used / kv_total if kv_total else 0.0
            )
            kv_col = (
                GREEN if util < 0.7 else YELLOW if util < 0.9 else RED
            )
            ttft99 = rep.get("ttft_p99_s")
            row = (
                f"  {rid:<8} {state_s}  "
                f"q {int(rep.get('queue_depth') or 0)}  "
                f"act {int(rep.get('active_sequences') or 0)}  "
                + c(kv_col, f"kv {100.0 * util:.0f}%")
                + (
                    f"  ttft p99<={ttft99:.3g}s"
                    if ttft99 is not None else ""
                )
                + f"  done {int(rep.get('requests_completed') or 0)}"
                + (
                    c(RED, f"  fail x{int(rep.get('failures') or 0)}")
                    if rep.get("failures") else ""
                )
            )
            lines.append(row)
        if fleet_qps is not None:
            lines.append(f"  fleet {fleet_qps:.2f} req/s")
    phases = m.get("phase_seconds_total") or {}
    if phases:
        lines.append(
            "phases      " + "  ".join(
                f"{dict(k).get('phase', '?')}={v:.1f}s"
                for k, v in sorted(phases.items())
            )
        )
    return "\n".join(lines)


# --------------------------------------------------------------- main loop


def make_source(target: str):
    if target.startswith(("http://", "https://")):
        return EndpointSource(target)
    return JsonlSource(target)


def find_predicted(target: str, explicit: str | None) -> str | None:
    """Resolve the fleetsim prediction file: ``--predicted`` wins; a
    file target auto-detects a sibling ``fleetsim.json`` in its run dir
    (endpoint targets have no local run dir to search)."""
    if explicit:
        return explicit
    if not target.startswith(("http://", "https://")):
        cand = os.path.join(
            os.path.dirname(os.path.abspath(target)), "fleetsim.json"
        )
        if os.path.isfile(cand):
            return cand
    return None


def find_predicted_serve(target: str, explicit: str | None) -> str | None:
    """Resolve the SERVE twin prediction file: ``--predicted-serve``
    wins; a file target auto-detects a sibling ``fleetsim_serve.json``
    (tools/fleetsim.py --serve -o) in its run dir."""
    if explicit:
        return explicit
    if not target.startswith(("http://", "https://")):
        cand = os.path.join(
            os.path.dirname(os.path.abspath(target)), "fleetsim_serve.json"
        )
        if os.path.isfile(cand):
            return cand
    return None


def load_predicted_serve(path: str | None) -> dict | None:
    """{"ratio", "ttft_p99", "path"} from a serve-mode fleetsim record;
    None when absent/unreadable (torn-file tolerant like
    `load_predicted`)."""
    if not path:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        if doc.get("taxonomy") != "serve":
            return None
        ttft = (
            ((doc.get("predicted") or {}).get("ttft") or {}).get("p99")
            or {}
        )
        return {
            "ratio": (
                float(doc["goodput_ratio"])
                if doc.get("goodput_ratio") is not None else None
            ),
            "ttft_p99": (
                float(ttft["value"])
                if ttft.get("value") is not None else None
            ),
            "path": path,
        }
    except (OSError, ValueError, TypeError, KeyError):
        return None


def load_predicted(path: str | None) -> dict | None:
    """{"ratio", "effective", "path"} from a fleetsim predicted record
    (tools/fleetsim.py -o); None when absent/unreadable - a dashboard
    never crashes on a half-written file."""
    if not path:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
        ratio = doc.get("goodput_ratio")
        if ratio is None:
            return None
        return {
            "ratio": float(ratio),
            "effective": (doc.get("metrics") or {}).get(
                "effective_goodput_ratio"
            ),
            "path": path,
        }
    except (OSError, ValueError, TypeError):
        return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "target",
        help="a live metrics endpoint (http://host:port[/metrics]) or a "
        "metrics JSONL path (--metrics-jsonl file) to tail",
    )
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh seconds (default 1)")
    ap.add_argument("--once", action="store_true",
                    help="render a single frame and exit (CI/scripting)")
    ap.add_argument("--no-color", action="store_true")
    ap.add_argument("--width", type=int, default=72)
    ap.add_argument("--predicted", metavar="FLEETSIM.json",
                    help="fleetsim predicted record for the goodput "
                    "predicted-vs-actual gap (auto-detected as "
                    "fleetsim.json next to a file target)")
    ap.add_argument("--predicted-serve", metavar="FLEETSIM_SERVE.json",
                    help="serve-twin predicted record for the serving "
                    "pane's predicted-vs-actual line (auto-detected as "
                    "fleetsim_serve.json next to a file target)")
    args = ap.parse_args(argv)

    src = make_source(args.target)
    predicted_path = find_predicted(args.target, args.predicted)
    predicted_serve_path = find_predicted_serve(
        args.target, args.predicted_serve
    )
    color = not args.no_color and sys.stdout.isatty()
    if args.once:
        color = not args.no_color and False
    try:
        while True:
            snap = src.sample()
            if snap is not None and predicted_path:
                # re-read each frame: a rerun of tools/fleetsim.py may
                # refresh the prediction mid-run
                snap["predicted"] = load_predicted(predicted_path)
            if snap is not None and predicted_serve_path:
                snap["predicted_serve"] = load_predicted_serve(
                    predicted_serve_path
                )
            if snap is None:
                err = getattr(src, "error", None)
                frame = (
                    f"live_top: no data from {args.target}"
                    + (f" ({err})" if err else "")
                )
            else:
                frame = render(snap, color=color, width=args.width)
            if args.once:
                print(frame)
                return 0 if snap is not None else 1
            # full-frame repaint: clear + home, no curses dependency
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
