#!/usr/bin/env python
"""launch: spawn and supervise an elastic multi-process training group.

The single-node elastic supervisor (`train/supervisor.py`,
docs/ROBUSTNESS.md "Elastic supervisor") as a CLI: N real OS processes
join one JAX runtime through the coordinator handshake
(`parallel/distributed.py initialize()` - the supervisor owns the port),
each worker's liveness rides a heartbeat file, and on a worker death the
group restarts with the survivors - an `lm_train.py --resume --elastic`
workload then reshards the newest consistent checkpoint onto the smaller
mesh and keeps training. When capacity returns, `--grow-after` rejoins it.

The worker command follows `--`; every argv element may carry the tokens
`{rank}` / `{nprocs}` / `{devices}` (current group size x
--devices-per-proc), re-substituted on every (re)launch.

Examples:
  # 3-worker CPU group, tiny LM, survives one induced SIGKILL at step 5
  python tools/launch.py --nprocs 3 --devices-per-proc 1 \\
      --chaos-kill-rank 2 --chaos-kill-at-step 5 --chaos-kill-signal KILL \\
      -- python lm_train.py --dp "{devices}" --steps 20 --stop-at-step 20 \\
         --batch-size 12 --checkpoint-dir ck --checkpoint-every 2 \\
         --resume --elastic

  # coordinator death (rank 0 hosts the JAX coordinator service)
  python tools/launch.py --nprocs 2 --chaos-kill-rank 0 \\
      --chaos-kill-at-step 3 -- python lm_train.py ...

Exit codes: 0 = the group completed; 3 = restart budget exhausted /
below --min-procs (SUPERVISOR ABORT names the last failure); 4 =
rendezvous never succeeded. One machine-readable
`SUPERVISOR_SUMMARY {json}` line is always printed. Every failure
restart (and any abort) also writes `<run-dir>/postmortem.json` - the
per-rank exit causes, last heartbeats, crash flight-recorder dumps, and
log tails of the generation that died (docs/OBSERVABILITY.md "Fleet
observability").
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        argv, command = argv[:split], argv[split + 1:]
    else:
        command = []
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--nprocs", type=int, required=True,
                   help="target worker-process count")
    p.add_argument("--devices-per-proc", type=int, default=1,
                   help="virtual CPU devices each worker contributes "
                   "(XLA_FLAGS --xla_force_host_platform_device_count; "
                   "--no-force-host-devices for real accelerators)")
    p.add_argument("--no-force-host-devices", action="store_true",
                   help="do not force host-platform device counts into "
                   "the workers' XLA_FLAGS (real TPU/GPU workers)")
    p.add_argument("--min-procs", type=int, default=1,
                   help="smallest group the supervisor will shrink to; "
                   "fewer survivors than this aborts")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="failure-restart budget for the whole run; "
                   "exhausted = SUPERVISOR ABORT, exit 3 (no crash loop)")
    p.add_argument("--restart-backoff", type=float, default=1.0,
                   metavar="SEC", help="base backoff between failure "
                   "restarts (doubles per restart, capped at 30s)")
    p.add_argument("--rendezvous-retries", type=int, default=2,
                   help="relaunches (fresh coordinator port) for groups "
                   "that die before every worker came up")
    p.add_argument("--rendezvous-timeout", type=float, default=120.0,
                   metavar="SEC", help="group must finish rendezvous "
                   "(every worker heartbeating) within this window")
    p.add_argument("--grace", type=float, default=10.0, metavar="SEC",
                   help="SIGTERM -> SIGKILL grace when stopping workers "
                   "(long enough for an emergency checkpoint)")
    p.add_argument("--failure-settle", type=float, default=0.5,
                   metavar="SEC",
                   help="after a worker death is detected, wait this long "
                   "(or until the group is fully down) before freezing "
                   "the failure set - a gang crash then restarts "
                   "same-size instead of being misread as partial")
    p.add_argument("--heartbeat-timeout", type=float, default=0.0,
                   metavar="SEC",
                   help="treat a worker whose training heartbeat is this "
                   "stale as dead (0 = exit codes only; the in-process "
                   "watchdog handles stalls by default)")
    p.add_argument("--grow-after", type=float, default=0.0, metavar="SEC",
                   help="after a shrunk group has been healthy this long, "
                   "restart at full size (planned, graceful - every "
                   "worker checkpoints first); 0 = never grow")
    p.add_argument("--poll", type=float, default=0.2, metavar="SEC")
    p.add_argument("--run-dir", default=None,
                   help="supervisor state dir (heartbeats, worker logs); "
                   "default ./supervisor_run")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve the SUPERVISOR's live metrics "
                   "(supervisor_group_size, worker_failures_total, "
                   "elastic_restarts_total, restart latency) PLUS the "
                   "federated fleet view - per-rank step/step-time "
                   "gauges, fleet_step_skew_seconds, fleet_straggler_rank "
                   "and, for workers started with their own "
                   "--metrics-port, scraped rank-labeled fleet_* "
                   "re-exports - on http://127.0.0.1:PORT/metrics; 0 = "
                   "ephemeral. Watch with tools/live_top.py (fleet view)")
    p.add_argument("--scrape-interval", type=float, default=2.0,
                   metavar="SEC",
                   help="how often the supervisor scrapes each worker's "
                   "/metrics endpoint for the federation (workers "
                   "advertise their URL in the heartbeat file; heartbeat-"
                   "derived fleet metrics flow regardless)")
    p.add_argument("--straggler-min-skew", type=float, default=0.25,
                   metavar="SEC",
                   help="smallest cross-rank step-arrival spread that "
                   "attributes a straggler (fleet_straggler_rank); "
                   "smaller spreads are lockstep noise at the poll "
                   "cadence and set the gauge to -1")
    p.add_argument("--chaos-kill-rank", type=int, action="append",
                   default=None, metavar="R",
                   help="fault injection (parallel/fault.py ProcessChaos): "
                   "kill worker R once its heartbeat reaches "
                   "--chaos-kill-at-step (repeatable, paired positionally "
                   "with the other --chaos-kill-* flags; rank 0 = "
                   "coordinator death)")
    p.add_argument("--chaos-kill-at-step", type=int, action="append",
                   default=None, metavar="N",
                   help="step threshold for the matching --chaos-kill-rank "
                   "(default 0 = as soon as it heartbeats)")
    p.add_argument("--chaos-kill-signal", action="append", default=None,
                   choices=("KILL", "TERM"), metavar="SIG",
                   help="signal for the matching --chaos-kill-rank: KILL "
                   "= hard crash (no emergency checkpoint), TERM = "
                   "preemption notice (cooperative checkpoint first)")
    args = p.parse_args(argv)
    if not command:
        p.error("worker command missing: tools/launch.py [flags] -- "
                "python lm_train.py ...")

    from distributed_neural_network_tpu.parallel.fault import (
        KillEvent,
        ProcessChaos,
    )
    from distributed_neural_network_tpu.train.supervisor import (
        FleetFederation,
        Supervisor,
        SupervisorConfig,
    )
    from distributed_neural_network_tpu.utils.obs import (
        MetricsRegistry,
        ObsServer,
    )

    chaos = None
    if args.chaos_kill_rank:
        ranks = args.chaos_kill_rank
        steps = args.chaos_kill_at_step or []
        sigs = args.chaos_kill_signal or []
        events = tuple(
            KillEvent(
                rank=r,
                at_step=steps[i] if i < len(steps) else 0,
                sig=sigs[i] if i < len(sigs) else "KILL",
            )
            for i, r in enumerate(ranks)
        )
        chaos = ProcessChaos(events=events)
    elif args.chaos_kill_at_step or args.chaos_kill_signal:
        p.error("--chaos-kill-at-step/--chaos-kill-signal configure "
                "--chaos-kill-rank, which was not given")

    cfg = SupervisorConfig(
        nprocs=args.nprocs,
        devices_per_proc=args.devices_per_proc,
        force_host_devices=not args.no_force_host_devices,
        min_procs=args.min_procs,
        max_restarts=args.max_restarts,
        restart_backoff_s=args.restart_backoff,
        rendezvous_retries=args.rendezvous_retries,
        rendezvous_timeout_s=args.rendezvous_timeout,
        grace_s=args.grace,
        failure_settle_s=args.failure_settle,
        heartbeat_timeout_s=args.heartbeat_timeout,
        grow_after_s=args.grow_after,
        poll_s=args.poll,
    )
    registry = MetricsRegistry()
    server = None
    if args.metrics_port is not None:
        server = ObsServer(registry, port=args.metrics_port)
        registry.mark_ready()
        print(f"(supervisor metrics: {server.url}/metrics)")
    sup = Supervisor(
        command,
        cfg,
        run_dir=args.run_dir or os.path.join(os.getcwd(), "supervisor_run"),
        chaos=chaos,
        registry=registry,
        federation=FleetFederation(
            registry,
            scrape_interval_s=args.scrape_interval,
            attrib_min_skew_s=args.straggler_min_skew,
        ),
    )
    try:
        return sup.run()
    finally:
        if server is not None:
            server.close()


if __name__ == "__main__":
    sys.exit(main())
