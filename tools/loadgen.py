#!/usr/bin/env python
"""Open-loop synthetic load generator for the serving stack
(`distributed_neural_network_tpu/serve/`).

OPEN loop: request arrival times are fixed by the offered rate alone -
a slow server does not slow the generator down, so queueing delay shows
up in the measured TTFT instead of being hidden by client backpressure
(the standard serving-benchmark discipline; closed-loop generators
underreport saturation).

  # 5 req/s for 20 s, mixed prompt lengths, streamed
  python tools/loadgen.py http://127.0.0.1:8000 --rate 5 --duration 20 \
      --prompt-lens 8,32,128 --max-new 32

  # fixed request count + a mid-flight client cancel + JSON summary
  python tools/loadgen.py URL --rate 10 --requests 50 --cancel-one \
      --out loadgen.json

  # burst mode: N requests fired at once (the 429 overflow probe)
  python tools/loadgen.py URL --burst 32 --requests 0 --expect-429

  # verify every streamed completion against the offline
  # models/transformer.py generate() oracle (the server's --seed /
  # geometry flags repeated here rebuild the same model)
  python tools/loadgen.py URL --rate 5 --requests 20 --check-oracle \
      --seed 0 --vocab 256 --d-model 64 --n-heads 4 --n-layers 2 \
      --d-ff 128

Measures per request: TTFT (send -> first streamed token), inter-token
gaps, completion status; reports offered/achieved req/s, p50/p99 TTFT,
p50/p99 inter-token latency, token throughput, and counts by outcome.
Exit codes: 0 ok; 1 a check failed (oracle mismatch, --expect-429
unmet, or any transport error); 2 usage error.
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import random
import sys
import threading
import time
import urllib.parse

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def percentile(xs, q: float):
    """Nearest-rank percentile; None when empty."""
    if not xs:
        return None
    s = sorted(xs)
    import math

    return s[max(0, math.ceil(q * len(s)) - 1)]


def make_prompts(n: int, lens, vocab: int, seed: int):
    """Deterministic mixed-length prompts (cycled lengths, seeded
    tokens >= 2 - ids 0/1 are conventionally pad/eos-ish)."""
    rng = random.Random(seed)
    lo = min(2, vocab - 1)
    out = []
    for i in range(n):
        ln = lens[i % len(lens)]
        out.append([rng.randrange(lo, vocab) for _ in range(ln)])
    return out


class RequestResult:
    __slots__ = ("idx", "status", "http_status", "tokens", "ttft_s",
                 "gaps_s", "total_s", "error", "prompt", "cancelled_after",
                 "req_id", "t_send_unix", "t_first_unix", "t_done_unix",
                 "replica", "router_retries")

    def __init__(self, idx, prompt):
        self.idx = idx
        self.prompt = prompt
        self.status = "pending"
        self.http_status = None
        self.tokens = []
        self.ttft_s = None
        self.gaps_s = []
        self.total_s = None
        self.error = None
        self.cancelled_after = None
        # the client half of the client-vs-server latency join
        # (tools/request_trace.py --client): the server's request id
        # echoed in the done frame, plus wall-clock edges
        self.req_id = None
        self.t_send_unix = None
        self.t_first_unix = None
        self.t_done_unix = None
        # fleet provenance (serve/fleet.py done frames): the replica
        # that finished the stream + failover re-dispatch count
        self.replica = None
        self.router_retries = 0


def run_one(
    base: str, res: RequestResult, *, max_new: int, api_key: str,
    temperature: float, timeout: float, cancel_after: int | None = None,
) -> None:
    """One streamed request; fills ``res`` in place. ``cancel_after``
    closes the connection after that many streamed tokens - the
    mid-flight client-disconnect probe."""
    u = urllib.parse.urlsplit(base)
    t0 = time.monotonic()
    res.t_send_unix = time.time()
    conn = http.client.HTTPConnection(
        u.hostname, u.port or 80, timeout=timeout
    )
    try:
        conn.request(
            "POST", "/v1/generate",
            json.dumps({
                "prompt": res.prompt, "max_new_tokens": max_new,
                "temperature": temperature, "stream": True,
            }),
            {"Content-Type": "application/json", "X-API-Key": api_key},
        )
        r = conn.getresponse()
        res.http_status = r.status
        if r.status != 200:
            res.status = (
                "rejected_429" if r.status == 429 else f"http_{r.status}"
            )
            r.read()
            return
        t_prev = None
        buf = b""
        while True:
            chunk = r.read(256)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                frame, buf = buf.split(b"\n\n", 1)
                line = frame.decode("utf-8", "replace").strip()
                if not line.startswith("data: "):
                    continue
                doc = json.loads(line[len("data: "):])
                now = time.monotonic()
                if "token" in doc:
                    res.tokens.append(int(doc["token"]))
                    if res.ttft_s is None:
                        res.ttft_s = now - t0
                        res.t_first_unix = time.time()
                    elif t_prev is not None:
                        res.gaps_s.append(now - t_prev)
                    t_prev = now
                    if (cancel_after is not None
                            and len(res.tokens) >= cancel_after):
                        res.status = "client_cancelled"
                        res.cancelled_after = len(res.tokens)
                        res.total_s = now - t0
                        res.t_done_unix = time.time()
                        conn.close()
                        return
                elif doc.get("done"):
                    res.status = "completed"
                    res.total_s = now - t0
                    res.t_done_unix = time.time()
                    if isinstance(doc.get("req_id"), int):
                        res.req_id = doc["req_id"]
                    if doc.get("replica") is not None:
                        res.replica = str(doc["replica"])
                    res.router_retries = int(
                        doc.get("router_retries") or 0
                    )
                    return
                elif "error" in doc:
                    res.status = "error"
                    res.error = doc["error"]
                    return
        res.status = "error"
        res.error = "stream ended without done frame"
    except OSError as e:
        res.status = "error"
        res.error = f"{type(e).__name__}: {e}"
    finally:
        conn.close()


def run_load(
    base: str, *, rate: float, n_requests: int, duration: float | None,
    prompt_lens, max_new: int, vocab: int, seed: int, api_keys,
    temperature: float, burst: int, cancel_one: bool, timeout: float,
    poisson: bool,
) -> dict:
    """Fire the schedule, join all clients, return the summary dict."""
    if duration is not None:
        n_requests = max(int(rate * duration), 1)
    n_total = n_requests + burst
    prompts = make_prompts(max(n_total, 1), prompt_lens, vocab, seed)
    results = [RequestResult(i, prompts[i]) for i in range(n_total)]
    cancel_idx = (
        burst + n_requests // 2 if cancel_one and n_requests > 0
        else (0 if cancel_one else None)
    )
    # the paced schedule is PRECOMPUTED (same rng stream as before) so
    # the exact seeded arrival offsets exist as data - exportable via
    # --arrival-trace for the serve twin to replay the identical stream
    rng = random.Random(seed + 1)
    offsets = []
    t_rel = 0.0
    for _ in range(n_requests):
        if poisson:
            t_rel += rng.expovariate(rate) if rate > 0 else 0.0
        else:
            t_rel += 1.0 / rate if rate > 0 else 0.0
        offsets.append(t_rel)
    schedule = [
        {
            "t_s": 0.0 if i < burst else round(offsets[i - burst], 9),
            "prompt_len": len(prompts[i]),
            "max_new_tokens": max_new,
        }
        for i in range(n_total)
    ]
    threads = []
    t_start = time.monotonic()

    def fire(res, cancel_after):
        th = threading.Thread(
            target=run_one, args=(base, res),
            kwargs=dict(
                max_new=max_new,
                api_key=api_keys[res.idx % len(api_keys)],
                temperature=temperature, timeout=timeout,
                cancel_after=cancel_after,
            ),
            daemon=True,
        )
        th.start()
        threads.append(th)

    # burst phase: all at once (the queue-overflow probe)
    for i in range(burst):
        fire(results[i], None)
    # paced open-loop phase
    t_paced = time.monotonic()
    for j in range(n_requests):
        i = burst + j
        delay = t_paced + offsets[j] - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        fire(results[i], 2 if i == cancel_idx else None)
    for th in threads:
        th.join(timeout=timeout + 60)
    wall = time.monotonic() - t_start

    by_status: dict = {}
    for r in results:
        by_status[r.status] = by_status.get(r.status, 0) + 1
    ttfts = [r.ttft_s for r in results if r.ttft_s is not None]
    gaps = [g for r in results for g in r.gaps_s]
    completed = [r for r in results if r.status == "completed"]
    toks = sum(len(r.tokens) for r in results)
    # fleet failover visibility: which replicas finished streams, and
    # how many requests needed a router re-dispatch to survive
    by_replica: dict = {}
    for r in completed:
        if r.replica is not None:
            by_replica[r.replica] = by_replica.get(r.replica, 0) + 1
    retried = [r for r in results if r.router_retries > 0]
    return {
        "offered_rps": round(rate, 4),
        "achieved_rps": round(len(completed) / wall, 4) if wall > 0 else None,
        "wall_s": round(wall, 3),
        "requests": n_total,
        "by_status": by_status,
        "tokens_streamed": toks,
        "tokens_per_s": round(toks / wall, 2) if wall > 0 else None,
        "ttft_p50_s": percentile(ttfts, 0.50),
        "ttft_p99_s": percentile(ttfts, 0.99),
        "intertoken_p50_s": percentile(gaps, 0.50),
        "intertoken_p99_s": percentile(gaps, 0.99),
        "by_replica": by_replica,
        "requests_retried": len(retried),
        "router_retry_episodes": sum(
            r.router_retries for r in retried
        ),
        "results": results,
        "schedule": schedule,
    }


def check_oracle(summary: dict, args) -> list:
    """Rebuild the server's seeded model offline and verify every
    COMPLETED request's streamed tokens equal `generate()`'s (greedy).
    Returns a list of problem strings."""
    sys.path.insert(0, _REPO)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_neural_network_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_heads=args.n_heads, n_layers=args.n_layers, d_ff=args.d_ff,
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
    )
    params = tfm.init_params(jax.random.key(args.seed), cfg)
    problems = []
    checked = 0
    for r in summary["results"]:
        if r.status == "completed":
            want = np.asarray(tfm.generate(
                params, jnp.asarray([r.prompt], jnp.int32), cfg,
                max_new_tokens=args.max_new,
            ))[0, len(r.prompt):]
            if r.tokens != [int(x) for x in want]:
                problems.append(
                    f"request {r.idx}: streamed {r.tokens} != oracle "
                    f"{[int(x) for x in want]}"
                )
            checked += 1
        elif r.status == "client_cancelled":
            # the cancelled prefix must still be oracle-exact
            want = np.asarray(tfm.generate(
                params, jnp.asarray([r.prompt], jnp.int32), cfg,
                max_new_tokens=max(len(r.tokens), 1),
            ))[0, len(r.prompt):][: len(r.tokens)]
            if r.tokens != [int(x) for x in want]:
                problems.append(
                    f"request {r.idx} (cancelled): prefix {r.tokens} "
                    f"!= oracle {[int(x) for x in want]}"
                )
            checked += 1
    if checked == 0:
        problems.append("oracle check had nothing to verify "
                        "(no completed requests)")
    else:
        print(f"(oracle: {checked} completion(s) verified against "
              "offline generate())", file=sys.stderr)
    return problems


def fetch_spec_stats(base: str, timeout: float) -> dict | None:
    """Best-effort GET /v1/status for the server's speculative-decoding
    counters (proposed/accepted draft tokens + acceptance rate). None
    when the server is unreachable or runs without --spec-decode."""
    import urllib.request

    try:
        with urllib.request.urlopen(
            base.rstrip("/") + "/v1/status", timeout=timeout
        ) as r:
            doc = json.loads(r.read())
    except (OSError, ValueError):
        return None
    if not doc.get("spec_decode"):
        return None
    return {
        "spec_decode": doc["spec_decode"],
        "spec_draft_layers": doc.get("spec_draft_layers"),
        "spec_proposed_tokens": doc.get("spec_proposed_tokens", 0),
        "spec_accepted_tokens": doc.get("spec_accepted_tokens", 0),
        "spec_steps": doc.get("spec_steps", 0),
        "spec_acceptance_rate": doc.get("spec_acceptance_rate"),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("url", help="server base URL (http://host:port)")
    p.add_argument("--rate", type=float, default=5.0,
                   help="offered request rate (req/s, open loop)")
    p.add_argument("--requests", type=int, default=20,
                   help="paced request count (0 = burst only)")
    p.add_argument("--duration", type=float, default=None,
                   help="pace for this many seconds instead of a count")
    p.add_argument("--poisson", action="store_true",
                   help="Poisson arrivals (seeded) instead of uniform")
    p.add_argument("--prompt-lens", default="4,8,16",
                   help="comma list of prompt lengths, cycled")
    p.add_argument("--max-new", type=int, default=16)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--api-keys", default="tenant0,tenant1",
                   help="comma list, assigned round-robin")
    p.add_argument("--burst", type=int, default=0,
                   help="requests fired all at once before pacing "
                   "(the 429 overflow probe)")
    p.add_argument("--cancel-one", action="store_true",
                   help="client-close one mid-flight stream after 2 "
                   "tokens (the disconnect-cancel probe)")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--expect-429", action="store_true",
                   help="fail (exit 1) unless at least one request was "
                   "rejected with 429")
    p.add_argument("--check-oracle", action="store_true",
                   help="verify streamed completions against offline "
                   "generate() (rebuilds the server's seeded model "
                   "from the flags below)")
    p.add_argument("--arrival-trace", default=None, metavar="OUT.json",
                   help="export the exact seeded arrival schedule "
                   "(times + prompt/max-token mix) for replay by "
                   "tools/fleetsim.py --serve --arrival-trace")
    p.add_argument("--out", default=None, help="write the JSON summary")
    p.add_argument("--out-requests", default=None,
                   help="write per-request JSONL (send / first-token / "
                   "done wall clocks, client-measured TTFT/E2E, the "
                   "server-echoed req_id) - the client half of "
                   "tools/request_trace.py --client")
    # model geometry for --check-oracle (must mirror the server's)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--d-ff", type=int, default=128)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--dtype", choices=("float32", "bfloat16"),
                   default="float32")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    try:
        lens = [int(x) for x in args.prompt_lens.split(",") if x.strip()]
        assert lens and all(x > 0 for x in lens)
    except (ValueError, AssertionError):
        print(f"loadgen: bad --prompt-lens {args.prompt_lens!r}",
              file=sys.stderr)
        return 2
    if args.requests <= 0 and args.burst <= 0 and not args.duration:
        print("loadgen: nothing to send (requests, burst both 0)",
              file=sys.stderr)
        return 2
    if args.check_oracle and args.temperature > 0:
        print("loadgen: --check-oracle needs greedy decoding "
              "(temperature 0)", file=sys.stderr)
        return 2

    summary = run_load(
        args.url, rate=args.rate, n_requests=max(args.requests, 0),
        duration=args.duration, prompt_lens=lens, max_new=args.max_new,
        vocab=args.vocab, seed=args.seed,
        api_keys=[k.strip() for k in args.api_keys.split(",") if k.strip()],
        temperature=args.temperature, burst=max(args.burst, 0),
        cancel_one=args.cancel_one, timeout=args.timeout,
        poisson=args.poisson,
    )

    problems = []
    errors = [r for r in summary["results"] if r.status == "error"]
    for r in errors[:5]:
        problems.append(f"request {r.idx} failed: {r.error}")
    if args.expect_429 and not summary["by_status"].get("rejected_429"):
        problems.append(
            "--expect-429: no request was rejected with 429 "
            f"(statuses: {summary['by_status']})"
        )
    if args.cancel_one and not summary["by_status"].get(
        "client_cancelled"
    ):
        problems.append("--cancel-one: the cancel probe did not cancel "
                        "(stream finished before 2 tokens?)")
    if args.check_oracle:
        problems.extend(check_oracle(summary, args))

    doc = {
        k: v for k, v in summary.items()
        if k not in ("results", "schedule")
    }
    spec = fetch_spec_stats(args.url, min(args.timeout, 10.0))
    if spec is not None:
        doc["spec"] = spec
    doc["ok"] = not problems
    doc["problems"] = problems
    print(json.dumps(doc, indent=1))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
    if args.arrival_trace:
        with open(args.arrival_trace, "w") as f:
            json.dump({
                "kind": "arrivals",
                "version": 1,
                "seed": args.seed,
                "rate": args.rate,
                "poisson": bool(args.poisson),
                "burst": max(args.burst, 0),
                "arrivals": summary["schedule"],
            }, f, indent=1)
            f.write("\n")
    if args.out_requests:
        with open(args.out_requests, "w") as f:
            for r in summary["results"]:
                f.write(json.dumps({
                    "idx": r.idx,
                    "req_id": r.req_id,
                    "status": r.status,
                    "http_status": r.http_status,
                    "n_tokens": len(r.tokens),
                    "ttft_s": (
                        round(r.ttft_s, 6) if r.ttft_s is not None
                        else None
                    ),
                    "e2e_s": (
                        round(r.total_s, 6) if r.total_s is not None
                        else None
                    ),
                    "t_send_unix": r.t_send_unix,
                    "t_first_token_unix": r.t_first_unix,
                    "t_done_unix": r.t_done_unix,
                    "replica": r.replica,
                    "router_retries": r.router_retries,
                }) + "\n")
    if problems:
        print("LOADGEN FAILED:", file=sys.stderr)
        for prob in problems:
            print(f"  - {prob}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
