#!/usr/bin/env bash
# Batch-size sweep - parity with the reference run_training.sh:1-4
# (`mpiexec -n 4 data_parallelism_train.py --nb-proc 4 --batch-size $bs`).
# No mpiexec: --nb-proc is the mesh device count. Extra args pass through
# (e.g. ./run_training.sh --data synthetic --epochs 2 for a smoke sweep).
set -euo pipefail
for bs in 1 2 4 8 16 32 64; do
  python data_parallelism_train.py --nb-proc 4 --batch-size "$bs" "$@"
done
