#!/usr/bin/env python
"""Generate REPORT.md: this framework's numbers against the reference's.

Reproduces the reference report's two experiment tables (Project_Report.pdf
Tables 1-2, mirrored in BASELINE.md / SURVEY.md section 6) on this
machine's devices and writes a markdown report with side-by-side
comparison:

- Table 1: device-count sweep (reference: 3-8 MPI procs, 25 epochs, bs 16)
- Table 2: batch-size sweep (reference: 4 procs, bs 1-64, 25 epochs)

Usage:
  python report.py                    # full sweeps, real data if present
  python report.py --quick            # 2-epoch smoke sweeps on synthetic
  python report.py --epochs 25 --data auto --out REPORT.md

The reference numbers are CPU wall-clock on an 8-core i7-9800X; `speedup`
is reference_train_s / ours on whatever devices are visible here. Accuracy
is only comparable when real CIFAR-10 is on disk (`data_source` is
recorded; synthetic accuracy is near-100% and NOT comparable).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import re
import sys

# SURVEY.md section 6 (report Tables 1-2 + measured child train logs)
REF_PROC = {  # procs -> (acc %, train_s)
    3: (64.4, 375.0), 4: (63.05, 794.0), 5: (60.93, 1127.0),
    6: (59.41, 1386.0), 7: (57.95, 1528.0), 8: (55.28, 1642.0),
}
# Train-time source of truth is bench.py's REFERENCE_BS_SWEEP_S (the
# measured child logs, e.g. bs16_log_epochs25_proc4_children.txt:2 =
# 701.8 s), NOT the reference report's published Table 2 (761 s at bs16)
# - the two differ because the published table includes overhead outside
# the child train metric; both artifacts must quote the SAME denominator
# or REPORT.md and BENCH_MATRIX.json contradict each other for one
# measurement. Accuracy has no child-log counterpart, so it stays from
# the published table.
from bench import REFERENCE_BS_SWEEP_S as _REF_BS_S

# artifact root: BENCH_MATRIX.json and tools/ tune files live beside
# this script; module-level so tests can point it at a synthetic tree
REPO = os.path.dirname(os.path.abspath(__file__))

_REF_BS_ACC = {1: 56.54, 2: 61.3, 4: 63.48, 8: 65.19, 16: 63.59,
               32: 57.68, 64: 50.86}
REF_BS = {bs: (_REF_BS_ACC[bs], _REF_BS_S[bs]) for bs in _REF_BS_ACC}


def run_one(nb_proc, batch_size, epochs, data, synthetic_size):
    from distributed_neural_network_tpu.train.measure import measure_dp_training

    return measure_dp_training(
        nb_proc=nb_proc, batch_size=batch_size, epochs=epochs,
        data=data, synthetic_size=synthetic_size,
    )


def fmt_row(cells):
    return "| " + " | ".join(str(c) for c in cells) + " |"


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=25)
    p.add_argument("--data", default="auto")
    p.add_argument("--synthetic-size", type=int, default=None)
    p.add_argument("--quick", action="store_true",
                   help="2 epochs, 2000 synthetic rows, reduced sweep points")
    p.add_argument("--from-matrix", action="store_true",
                   help="render the CNN tables from BENCH_MATRIX.json's "
                   "25-epoch cnn rows instead of re-measuring (one bench "
                   "run feeds both artifacts; saves ~10 min of chip time)")
    p.add_argument("--out", default="REPORT.md")
    args = p.parse_args()

    from distributed_neural_network_tpu.train.cli import honor_platform_env

    honor_platform_env()

    epochs = 2 if args.quick else args.epochs
    syn = 2000 if args.quick else args.synthetic_size
    data = "synthetic" if args.quick else args.data

    if args.from_matrix:
        # NEVER touch the jax backend on this path: rendering a report
        # must not claim the TPU (r4 post-mortem - a report.py blocked on
        # a busy claim was killed at its stage timeout, wedging the chip
        # for the rest of the session). Device identity comes from the
        # measured rows themselves.
        proc_rows, bs_rows, pending_bs = _rows_from_matrix(epochs)
        any_row = (proc_rows or bs_rows or [None])[0]
        if any_row is None:
            # still render: the LM/bubble/scaling sections and the
            # accuracy-parity wording carry their own evidence, and the
            # CNN tables show honest pending cells rather than the whole
            # report going missing when the chip was unavailable
            print("note: no measured 25-epoch cnn rows in "
                  "BENCH_MATRIX.json; CNN tables render as pending",
                  file=sys.stderr)
            ndev, bs_devices = 1, 1
            device_desc = ("device pending (no measured cnn rows in "
                           "BENCH_MATRIX.json)")
        else:
            # device identity / data source come from whichever sweep has
            # measured rows (the headline bs16 row may be the missing one)
            ndev = any_row.get("devices", 1)
            bs_devices = bs_rows[0]["devices"] if bs_rows else min(4, ndev)
            device_desc = (
                f"{ndev}x "
                f"{any_row.get('device_kind', 'unknown device')} "
                f"({any_row.get('platform', '?')}, from matrix rows)"
            )
    else:
        import jax

        ndev = jax.device_count()
        dev0 = jax.devices()[0]
        device_desc = f"{ndev}x {dev0.device_kind} ({dev0.platform})"
        procs = sorted({d for d in REF_PROC if d <= ndev} | {min(ndev, 8)})
        bss = [4, 16, 64] if args.quick else list(REF_BS)

        proc_rows, bs_rows, pending_bs = [], [], []
        for n in procs:
            r = run_one(n, 16, epochs, data, syn)
            r["ref"] = REF_PROC.get(n)
            proc_rows.append(r)
            print(json.dumps(r), file=sys.stderr)
        bs_devices = min(4, ndev)
        for bs in bss:
            r = run_one(bs_devices, bs, epochs, data, syn)
            r["ref"] = REF_BS.get(bs)
            bs_rows.append(r)
            print(json.dumps(r), file=sys.stderr)

    src_row = (proc_rows or bs_rows or [{}])[0]
    src = src_row.get("source", "synthetic")
    lines = [
        "# REPORT - measured results vs the reference",
        "",
        f"Generated {datetime.datetime.now():%Y-%m-%d %H:%M} by `report.py` "
        f"on {device_desc}; "
        f"data source: **{src}**; {epochs} epochs per run.",
        "",
        "Reference numbers: Project_Report.pdf Tables 1-2 (8-core i7-9800X,"
        " 25 epochs; SURVEY.md section 6). `speedup` = reference train time /"
        " ours. Accuracy columns are only comparable on real CIFAR-10"
        " (synthetic accuracy is near-100% by construction)."
        if src != "synthetic" else
        "**Synthetic data run** - wall-clock comparable (identical shapes"
        " and FLOPs), accuracy NOT comparable to the reference.",
        "",
        "## Table 1 - device-count sweep (bs=16)",
        "",
    ]
    base = max(proc_rows, key=lambda r: r["devices"], default=None)
    if base and base["train_s"] > 0:
        ref8 = REF_PROC[8]
        lines += [
            f"Headline: {epochs} epochs at bs=16 on {base['devices']} "
            f"device(s) = **{base['train_s']:.2f} s** vs the reference's "
            f"8-process run ({ref8[1]:.0f} s at 25 ep) -> "
            f"**{ref8[1] * epochs / 25.0 / base['train_s']:.0f}x** "
            "(epoch-prorated).",
            "",
        ]
    lines += [
        fmt_row(["devices", "val acc %", "train s",
                 "ref acc % (N procs)", "ref train s", "speedup"]),
        fmt_row(["---"] * 6),
    ]
    def ref_cells(r):
        """Reference acc/time cells + epoch-prorated speedup (ref is 25 ep)."""
        ref = r["ref"]
        if not ref or r["train_s"] <= 0:
            return ["-", "-", "-"]
        prorated = ref[1] * epochs / 25.0
        return [f"{ref[0]:.2f}", f"{ref[1]:.0f}",
                f"{prorated / r['train_s']:.0f}x"]

    for r in proc_rows:
        lines.append(fmt_row([
            r["devices"], f"{r['val_acc']:.2f}", f"{r['train_s']:.2f}",
            *ref_cells(r),
        ]))
    if not proc_rows:
        lines.append(fmt_row(
            ["*pending measurement (chip unavailable)*"] + ["-"] * 5
        ))
    lines += [
        "",
        f"## Table 2 - batch-size sweep ({bs_devices} device"
        f"{'s' if bs_devices != 1 else ''}; reference used 4 MPI procs)",
        "",
        fmt_row(["batch size", "val acc %", "train s",
                 "ref acc %", "ref train s", "speedup"]),
        fmt_row(["---"] * 6),
    ]
    # measured and pending rows merged in bs order so the sweep column
    # stays monotonic whichever subset measured
    merged = sorted(
        [("row", r["batch_size"], r) for r in bs_rows]
        + [("pending", bs, None) for bs in pending_bs],
        key=lambda t: t[1],
    )
    field_notes = []
    for kind, bs, r in merged:
        if kind == "row":
            note = r.get("field_note")
            if note:
                field_notes.append(f"bs {bs}: {note}")
            lines.append(fmt_row([
                f"{bs}*" if note else bs,
                f"{r['val_acc']:.2f}", f"{r['train_s']:.2f}",
                *ref_cells(r),
            ]))
        else:
            # unmeasured stub row: show the reference cells so the
            # sweep's full bs range stays visible, value cells pending
            ref = REF_BS.get(bs)
            lines.append(fmt_row([
                bs, "*pending*", "*pending (not yet measured)*",
                f"{ref[0]:.2f}" if ref else "-",
                f"{ref[1]:.0f}" if ref else "-", "-",
            ]))
    if not bs_rows and not pending_bs:
        lines.append(fmt_row(
            ["*pending measurement (chip unavailable)*"] + ["-"] * 5
        ))
    for n in field_notes:  # provenance of any id<->field repair, visible
        lines.append(f"\n\\* {n}")
    lines += [
        "",
        "Notes: the reference's N procs = 1 idle parent + N-1 workers over "
        "1/(N-1) data shards; here all N devices train on 1/N shards "
        "(SURVEY.md section 7, topology remap). Train time here is the "
        "fused multi-epoch span (training + parameter sync; eval outside), "
        "matching the reference's child train-time metric.",
        "",
        (
            "Accuracy parity: this run used real CIFAR-10 "
            f"(data source: {src}), so the accuracy columns above compare "
            "directly against the reference's 63-66% band "
            "(Project_Report.pdf Tables 1-2). Semantic fidelity is "
            "additionally proven by `tests/test_oracle.py`: the engine's "
            "faithful path matches an independent pure-numpy "
            "implementation of the reference algorithm "
            "(`tests/oracle_numpy.py`) step-for-step."
            if src != "synthetic"
            else
            "Accuracy parity: no real CIFAR-10 exists in this "
            "environment, so the accuracy claim is worded as "
            "*algorithm-identical; band pending real data*, verified "
            "three ways. (1) Semantic fidelity: `tests/test_oracle.py` "
            "proves the engine's faithful path computes the reference's "
            "exact algorithm (contiguous shards, per-epoch momentum-reset "
            "SGD, epoch-edge parameter averaging) step-for-step against "
            "an independent pure-numpy implementation "
            "(`tests/oracle_numpy.py`) - params and global train loss "
            "match epoch-by-epoch, and the test fails if any semantic "
            "knob (e.g. momentum reset) is changed. "
            f"(2) Reference-scale trajectory: {_oracle_fullscale_line()} "
            "(3) Ready-to-run real-data path: drop "
            "`cifar-10-batches-py/` (or `cifar10.npz`) under `./data` "
            "and run `python report.py --data pickle --epochs 25` - the "
            "same engine is then expected to land in the reference's "
            "63-66% accuracy band (Project_Report.pdf Tables 1-2)."
        ),
        "",
    ]
    lines += _bench_matrix_sections()
    lines += _flash_tune_sections()
    lines += _mfu_ceiling_section()
    with open(args.out, "w") as f:
        f.write("\n".join(lines))
    print(f"wrote {args.out}")
    return 0


def _mfu_ceiling_section() -> list[str]:
    """Arithmetic MFU ceiling for the flagship LM row, from measured data.

    VERDICT r3 item 2 asks for >=40% MFU or a written ablation proving
    the ceiling. This derives the ceiling directly: the tune file's best
    own-kernel fwd+bwd wall-clock is EXACTLY one layer's attention at
    the flagship step shape (B16 x H8 x S2048 x Dh64), so

        step_time >= L * attn_wall + (non-attention FLOPs) / peak

    even if every matmul ran at 100% MXU. Ceiling MFU = step FLOPs /
    (peak * that bound). Rendered only when both the tune file and the
    flagship matrix row exist; all inputs are cited measured artifacts.
    """
    import glob

    from distributed_neural_network_tpu.models.transformer import (
        TransformerConfig,
    )
    from distributed_neural_network_tpu.train.measure import (
        model_flops_per_token,
        peak_flops,
    )

    here = REPO
    # the ceiling is only published for a flagship row that actually
    # exists in the matrix, with the model read FROM that row (a
    # hardcoded config could silently diverge from the bench spec)
    try:
        with open(os.path.join(here, "BENCH_MATRIX.json")) as f:
            rows = json.load(f).get("rows", [])
    except (OSError, json.JSONDecodeError):
        return []
    flag = next((r for r in rows
                 if r.get("id") == "lm_flash_d512_L8_seq2048_bf16"
                 and "tokens_per_s" in r), None)
    if flag is None:
        return []
    # older-format rows (r3) may lack some fields; fall back to the bench
    # spec's defaults for exactly this row id
    flag.setdefault("n_heads", 8)
    flag.setdefault("d_ff", 2048)
    flag.setdefault("vocab", 32768)
    seq, batch = flag["seq_len"], flag["batch"]
    head_dim = flag["d_model"] // flag["n_heads"]
    # matching tune file: same seq; shape must match the row's geometry
    paths = sorted(glob.glob(
        os.path.join(here, "tools", f"flash_tune_*_s{seq}*.json")))
    tune = None
    for p in paths:
        try:
            with open(p) as f:
                cand = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        s = cand.get("shape", {})
        if (s.get("seq") == seq and s.get("batch") == batch
                and s.get("heads") == flag["n_heads"]
                and s.get("head_dim") == head_dim
                and cand.get("best_own_ms")):
            tune, tune_path = cand, p
            break
    if tune is None:
        return []
    attn_ms = tune["best_own_ms"]
    kind = str(tune.get("device", "")).replace("_", " ")
    peak = peak_flops(kind, "bfloat16")
    if not peak:
        return []
    cfg = TransformerConfig(
        vocab_size=flag["vocab"], d_model=flag["d_model"],
        n_heads=flag["n_heads"], n_layers=flag["n_layers"],
        d_ff=flag.get("d_ff", 2048),
    )
    L = cfg.n_layers
    flops_tok = model_flops_per_token(cfg, seq)
    step_flops = flops_tok * batch * seq
    # attention share of the model-FLOP count (the 4*S*d term, x3 fwd+bwd)
    attn_flops = 3.0 * L * 4 * seq * cfg.d_model * batch * seq
    non_attn = step_flops - attn_flops
    attn_wall = L * attn_ms / 1e3
    bound = attn_wall + non_attn / peak
    ceiling = step_flops / (peak * bound) * 100.0
    ideal = step_flops / peak
    target_attn_ms = (step_flops / (0.40 * peak) - non_attn / peak) / L * 1e3
    achieved = flag.get("mfu_pct")
    ach = (f"measured {achieved}% on that row, " if achieved else "")
    # the config-level route past the d512 ceiling: best measured MFU
    # over ALL LM rows (r5: d1024/hd128/dots_saveable landed 53.73%)
    best = max((r for r in rows
                if r.get("id", "").startswith("lm_")
                and isinstance(r.get("mfu_pct"), (int, float))),
               key=lambda r: r["mfu_pct"], default=None)
    if best is not None and best["mfu_pct"] >= 40.0 \
            and best["id"] != flag["id"]:
        # the kernel-budget clause must track the actual comparison -
        # this branch is selected on best-row MFU alone (r5 review)
        kernel_clause = (
            "the tuned kernel is UNDER it, and the remaining gap on "
            "this row is matmul-side efficiency (d512 matmuls are "
            "narrow for the MXU)"
            if attn_ms <= target_attn_ms else
            f"the tuned kernel ({attn_ms:.1f} ms/layer) is still OVER it"
        )
        tail = (
            f"The 40% target at this shape implies an attention budget "
            f"of <= {target_attn_ms:.1f} ms/layer; {kernel_clause}. The "
            "config-level route closes it: the target is MET at "
            f"**{best['mfu_pct']}% measured MFU** on `{best['id']}` "
            f"(d{best.get('d_model')}, Dh="
            f"{best.get('d_model', 0) // max(best.get('n_heads', 1), 1)} "
            "head geometry"
            + (", dots_saveable remat" if best.get("remat_policy") else "")
            + " - the LM table row)."
        )
    elif attn_ms <= target_attn_ms:
        # the (re-)tuned kernel fits the 40% attention budget: the
        # ceiling no longer binds at the target - what remains is
        # matmul-side efficiency plus re-measuring the row with these
        # blocks (the measured row predates the tune that got here)
        tail = (
            f"The 40% target at this shape implies an attention budget "
            f"of <= {target_attn_ms:.1f} ms/layer, and the tuned kernel "
            f"is now UNDER it - the kernel ceiling no longer rules out "
            "the target. What stands between the measured row (which "
            "predates this kernel tuning) and the ceiling is matmul-side "
            "efficiency plus re-measuring the flagship row with these "
            "blocks (queued for the next healthy-chip session); "
            "larger-d_model rows (attention is a smaller FLOP fraction) "
            "remain the config-level route to even higher MFU."
        )
    else:
        tail = (
            "Reaching the 40% target at this shape requires attention "
            f"at <= {target_attn_ms:.1f} ms/layer "
            f"({attn_ms / max(target_attn_ms, 1e-9):.1f}x faster than "
            "measured) - the kernel, not the surrounding program, is "
            "the binding constraint; larger-d_model rows (attention is "
            "a smaller FLOP fraction) are the config-level route past "
            "it."
        )
    return [
        "## MFU ceiling - flagship LM row, derived from measured kernels",
        "",
        f"At d{cfg.d_model}/L{L}/seq{seq}/bs{batch} the step computes "
        f"{step_flops / 1e12:.2f} model TFLOP "
        f"(ideal {ideal * 1e3:.0f} ms at the {peak / 1e12:.0f} TF/s bf16 "
        f"peak). The tuned own flash kernel measures {attn_ms:.1f} ms "
        "fwd+bwd for ONE layer's attention at exactly this shape "
        f"(`{os.path.basename(tune_path)}`, best_own_ms), so attention "
        f"alone costs {attn_wall * 1e3:.0f} ms/step across {L} layers. "
        "Even with every non-attention matmul at 100% MXU utilization, "
        f"step time >= {bound * 1e3:.0f} ms -> **MFU <= {ceiling:.0f}%** "
        f"with the current kernel ({ach}the gap to the ceiling is the "
        f"matmul side). {tail}",
        "",
    ]


def _oracle_fullscale_line() -> str:
    """One sentence summarizing tools/oracle_fullscale_result.json."""

    path = os.path.join(REPO, "tools", "oracle_fullscale_result.json")
    pending = ("`tools/oracle_fullscale.py` runs the same parity check at "
               "the reference's full scale (25 epochs x 50k rows x 8 "
               "workers); artifact pending.")
    try:
        with open(path) as f:
            r = json.load(f)
        s = r["scale"]
        s["epochs"], s["rows"], s["workers"]
        r["worst_loss_abs_diff"], r["worst_param_max_rel_err"], r["wall_s"]
    except (OSError, json.JSONDecodeError, KeyError, TypeError):
        return pending
    # never render a smoke-scale or failed artifact as the full-scale
    # verification claim
    full = (s["epochs"] >= 25 and s["rows"] >= 50000 and s["workers"] >= 8)
    if not r.get("ok") or not full:
        return (pending[:-1] +
                f" (current artifact: ok={r.get('ok')}, {s['epochs']} "
                f"epochs x {s['rows']} rows - not the full-scale claim).")
    return (
        f"`tools/oracle_fullscale_result.json` (ok={r['ok']}) matches the "
        f"engine against the f64 numpy oracle at the reference's full "
        f"scale - {s['epochs']} epochs x {s['rows']} rows x "
        f"{s['workers']} workers, bs {s['batch_size']}: worst per-epoch "
        f"loss diff {r['worst_loss_abs_diff']:.1e}, worst param rel err "
        f"{r['worst_param_max_rel_err']:.1e} over the whole horizon "
        f"(float-precision drift of the same algorithm, "
        f"{r['wall_s'] / 60:.0f} min wall)."
    )


def _rows_from_matrix(epochs: int):
    """(proc_rows, bs_rows, pending_bs) from BENCH_MATRIX.json cnn rows.

    The bench matrix's cnn_dp_ep{epochs}_bs{N} rows carry exactly the
    fields `run_one` returns (devices/batch_size/val_acc/train_s/source),
    measured by the same `measure_dp_training` - so the report can render
    from one bench run instead of re-measuring the whole sweep.
    """

    path = os.path.join(REPO, "BENCH_MATRIX.json")
    try:
        with open(path) as f:
            rows = json.load(f).get("rows", [])
    except (OSError, json.JSONDecodeError):
        return [], [], []
    by_bs = {}
    pending_bs = []
    for r in rows:
        rid = r.get("id", "")
        if (rid == f"cnn_dp_ep{epochs}_bs{r.get('batch_size')}"
                and "train_s" in r):
            by_bs[r["batch_size"]] = dict(r)
        else:
            # error/skipped stubs of the plain bs sweep (no kernel/dtype
            # suffix): Table 2 must show the reference's bs values as
            # pending rather than silently shrinking the sweep
            m = re.fullmatch(rf"cnn_dp_ep{epochs}_bs(\d+)", rid)
            if m and "train_s" not in r:
                pending_bs.append(int(m.group(1)))
            elif m:
                # measured, but the batch_size field is missing or
                # disagrees with the id: render it (bs from the id)
                # instead of silently dropping a measured row - the
                # silent-shrink this function exists to prevent
                fixed = dict(r)
                fixed["batch_size"] = int(m.group(1))
                fixed["field_note"] = (
                    f"batch_size field was {r.get('batch_size')!r}; "
                    "bs taken from the row id")
                by_bs[int(m.group(1))] = fixed
    proc_rows = []
    if 16 in by_bs:
        r = dict(by_bs[16])
        r["ref"] = REF_PROC.get(8)  # headline comparison: the 8-proc run
        proc_rows.append(r)
    bs_rows = []
    for bs in sorted(by_bs):
        r = dict(by_bs[bs])
        r["ref"] = REF_BS.get(bs)
        bs_rows.append(r)
    pending_bs = sorted(b for b in set(pending_bs) if b not in by_bs)
    return proc_rows, bs_rows, pending_bs


def _unmeasured_cell(r: dict) -> str:
    """One cell for a row without a measured value: states the fact and
    carries the recorded error - no claim about queue state (whether a
    re-measure is scheduled lives in ROADMAP.md, not in the row)."""
    why = str(r.get("error", r.get("skipped", "no measurement")))
    # strip ANSI color codes (backend error strings embed them) and
    # collapse whitespace (multi-line tracebacks break the markdown
    # table at the first newline - r5 review) before truncating
    why = re.sub(r"\x1b\[[0-9;]*m", "", why)
    why = " ".join(why.split())
    return f"no measured value (error: {why[:60].rstrip('; (')})"



def _hd_suffix(r: dict) -> str:
    """Head-geometry label, shown only for the non-default Dh (suffixing
    every row would split the r3/r4 A/B pairs that share the hd64
    default). Used by both the LM and decode tables - decode per-step
    cost and LM MFU are both geometry-bound."""
    if r.get("n_heads") and r["d_model"] // r["n_heads"] != 64:
        return f"/hd{r['d_model'] // r['n_heads']}"
    return ""


def _bench_matrix_sections() -> list[str]:
    """LM-throughput/MFU + pipeline-bubble sections from BENCH_MATRIX.json.

    bench.py writes the matrix incrementally on every run; rendering it
    here (rather than hand-editing REPORT.md) keeps the report
    regenerable in one command. Rows with errors are listed as such -
    an honest artifact beats a silently dropped row.
    """

    path = os.path.join(REPO, "BENCH_MATRIX.json")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        matrix = json.load(f)
    rows = matrix.get("rows", [])
    out = []

    # CNN kernel/dtype/input variants of the headline row: without this
    # section the bs16_{pallas,bf16,stream} rows render nowhere (Table 2
    # matches only the suffix-free bs-sweep ids)
    variants = []
    for r in rows:
        m = re.fullmatch(r"cnn_dp_ep(\d+)_bs16_(pallas|bf16|stream)",
                         r.get("id", ""))
        if m:
            variants.append((r, m.group(2), int(m.group(1))))
    # headline per epoch count: rows from other --epochs runs persist in
    # the matrix, and a cross-epoch "vs headline" ratio would be bogus
    heads = {}
    for r in rows:
        m = re.fullmatch(r"cnn_dp_ep(\d+)_bs16", r.get("id", ""))
        if m and "train_s" in r:
            heads[int(m.group(1))] = r
    if variants:
        desc = {
            "pallas": "fused Pallas CNN head (`ops/pallas_kernels.py`)",
            "bf16": "bfloat16 compute dtype",
            "stream": "host-streaming input, double-buffered prefetch",
        }
        eps = sorted({ep for _, _, ep in variants})
        out += [
            "## CNN variants - headline shape "
            f"({'/'.join(str(e) for e in eps)} ep, bs 16), one knob "
            "each",
            "",
            fmt_row(["variant", "epochs", "val acc %", "train s",
                     "vs same-epoch headline (hbm/f32)"]),
            fmt_row(["---"] * 5),
        ]
        stream_measured = False
        for r, kind, ep in variants:
            head = heads.get(ep)
            if "train_s" in r:
                stream_measured |= kind == "stream"
                # sub-0.01 ratios (e.g. headline 4 s vs stream 964 s)
                # rounded to "0.00x" - print the inverse as "Nx slower"
                # so the comparison stays recoverable (r5 review)
                if head and r["train_s"] > 0:
                    ratio = head["train_s"] / r["train_s"]
                    vs = (f"{ratio:.2f}x" if ratio >= 0.01
                          else f"{1 / ratio:.0f}x slower")
                else:
                    vs = "-"
                out.append(fmt_row([
                    desc[kind], ep, f"{r['val_acc']:.2f}",
                    f"{r['train_s']:.2f}", vs,
                ]))
            else:
                out.append(fmt_row(
                    [desc[kind], ep, "-", _unmeasured_cell(r), "-"]))
        out.append("")
        if stream_measured:
            out += [
                "The stream row runs the per-epoch engine path: "
                "streaming input has no fused multi-epoch span "
                "(`train/engine.py run` downgrades with a log line), and "
                "every batch is a host->device transfer that pays the "
                "tunnel round-trip the HBM-resident rows pay once for "
                "the whole dataset (~78k transfers at 25 ep/bs 16 - the "
                "dominant term on this tunneled backend; on a local TPU "
                "host the same path is bounded by PCIe/DMA, not RTT). "
                "Attribute only the remainder to the input pipeline "
                "itself.",
                "",
            ]

    lm = [r for r in rows if r.get("id", "").startswith("lm_")
          and not r.get("id", "").startswith("lm_decode")
          and "_scaling_" not in r.get("id", "")]
    if lm:
        out += [
            "## LM throughput - single chip (beyond-reference model family)",
            "",
            "Transformer LM (`lm_train.py`), synthetic copy task, "
            "steady-state tokens/s over the timed steps. Timing uses the "
            "hard value-fetch fence (`utils/timers.py hard_block`; "
            "`block_until_ready` alone is a no-op on the tunneled axon "
            "backend - numbers recorded before round 3's fence fix were "
            "dispatch time and have been discarded). MFU = model "
            "FLOPs/token x tokens/s / dtype-adjusted peak "
            "(`train/measure.py`; PaLM-appendix convention - causal "
            "attention counted at full S, not halved. The flash kernel "
            "skips fully-masked blocks, so at attention-dominated "
            "lengths the convention credits that skipped work: this is "
            "why MFU RISES with seq in the long-context rows; hardware "
            "MXU occupancy is lower there, and cross-seq comparisons "
            "hold on the stated convention, as published MFU numbers "
            "do). Kernel provenance: `pallas-flash` "
            "(no suffix) = the LIBRARY kernel (rows measured in r3, "
            "before the own kernels existed); `pallas-flash-own` / "
            "`pallas-flash-lib` = this framework's vma-typed 3-D-grid "
            "kernels vs the library A/B baseline (r4+).",
            "",
            fmt_row(["config", "attn", "remat", "batch", "seq",
                     "tokens/s", "MFU %"]),
            fmt_row(["---"] * 7),
        ]
        # measured rows first (best MFU at the top); unmeasured stubs below
        for r in sorted(lm, key=lambda r: ("tokens_per_s" not in r,
                                           -(r.get("mfu_pct") or 0))):
            if "tokens_per_s" not in r:
                out.append(fmt_row([
                    r["id"], "-", "-", "-", "-", _unmeasured_cell(r), "-",
                ]))
                continue
            cfgs = (f"d{r['d_model']}/L{r['n_layers']}{_hd_suffix(r)}"
                    f"/voc{r['vocab']//1000}k/{r['dtype']}")
            # a remat policy qualifies block remat (dots_saveable stores
            # matmul outputs; recompute is elementwise-only, so its FLOP
            # tax is a few percent, not full remat's ~1/3)
            remat = ("block/" + r["remat_policy"].replace("_saveable", "")
                     if r.get("remat") and r.get("remat_policy")
                     else "block" if r.get("remat")
                     else "attn" if r.get("remat_attn") else "none")
            out.append(fmt_row([
                cfgs, r.get("attn_kernel", r["attn"]), remat,
                r["batch"], r["seq_len"], f"{r['tokens_per_s']:,}",
                r.get("mfu_pct", "-"),
            ]))
        out.append("")

    dec = [r for r in rows if r.get("id", "").startswith("lm_decode")]
    if dec:
        out += [
            "## KV-cache decode throughput - single chip (inference path)",
            "",
            "Autoregressive generation (`models/transformer.py generate`): "
            "per-step AVERAGE cost at a stated static cache size "
            "(`train/measure.py measure_lm_decode`; every cached step "
            "attends the full padded cache, so the rate is a function of "
            "cache length - both sizes are shown, their spread is the "
            "measured cache-length scaling). Decode streams every "
            "parameter once per step, so utilization is reported against "
            "peak HBM BANDWIDTH (the binding resource), not the MXU peak.",
            "",
            fmt_row(["config", "batch", "cache len", "tok/s", "ms/step",
                     "HBM util %"]),
            fmt_row(["---"] * 6),
        ]
        # measured rows first, same as the LM table
        for r in sorted(dec, key=lambda r: "decode_tokens_per_s" not in r):
            if "decode_tokens_per_s" not in r:
                out.append(fmt_row([
                    r["id"], "-", "-", _unmeasured_cell(r), "-", "-",
                ]))
                continue
            cfgs = (f"d{r['d_model']}/L{r['n_layers']}{_hd_suffix(r)}"
                    f"/voc{r['vocab'] // 1000}k/{r['dtype']}")
            caches = [c for c in (r.get("at_cache_short"),
                                  r.get("at_cache_long")) if c]
            if not caches:
                # row measured under an older measure_lm_decode format
                # (top-level fields only) - render it rather than drop it
                caches = [{
                    "cache_len": "-",
                    "tokens_per_s": r["decode_tokens_per_s"],
                    "ms_per_step": r.get("ms_per_step", "-"),
                }]
            for i, c in enumerate(caches):
                is_last = i == len(caches) - 1
                out.append(fmt_row([
                    cfgs, r["batch"], c["cache_len"],
                    f"{c['tokens_per_s']:,}", c["ms_per_step"],
                    r.get("hbm_util_pct", "-") if is_last else "-",
                ]))
        out.append("")

    pb = [r for r in rows if r.get("id", "").startswith("pp4_bubble")
          and "configs" in r]
    if pb:
        r = pb[-1]
        out += [
            "## Pipeline bubble - measured at pp=4 "
            f"({r['devices']}x {r['platform']} mesh)",
            "",
            "Fixed microbatch size, varying (M microbatches, v interleave):"
            " tokens/s tracks 1 - bubble since per-token work is identical"
            " across configs (`train/measure.py measure_pp_bubble`). The"
            " interleaved (circular) schedule cuts the bubble to"
            " (P-1)/(v*M+P-1) (`parallel/pipeline.py`).",
            "",
            fmt_row(["microbatches", "interleave", "tokens/s",
                     "bubble (analytic)", "bubble (measured)",
                     "bubble (overhead-adjusted)"]),
            fmt_row(["---"] * 6),
        ]
        for c in r["configs"]:
            out.append(fmt_row([
                c["microbatches"], c["interleave"],
                f"{c['tokens_per_s']:,}", c["bubble_analytic"],
                c["bubble_measured"],
                c.get("bubble_overhead_adjusted", "-"),
            ]))
        tm = r.get("tick_model") or {}
        fit = (f" Tick-model fit over {tm.get('n_configs', '?')} "
               f"configs: per-layer {tm.get('per_layer_s')}s, "
               f"per-tick overhead {tm.get('per_tick_overhead_s')}s, "
               f"relative residual {tm.get('rel_fit_err')}. A NEGATIVE "
               "overhead-adjusted cell means that config ran faster than "
               "the fitted tick model predicts (fit residual, not a "
               "physical negative bubble) - read those cells as ~0."
               if tm else "")
        bnd = tm.get("boundary_solution")
        if bnd and tm.get("per_tick_overhead_s") == 0:
            fit += (
                " The overhead component sits on the o=0 boundary of "
                "the constrained (non-negative) fit - the unconstrained "
                "optimum is slightly negative "
                f"({bnd.get('per_tick_overhead_s_unconstrained')}s; "
                "later ticks run warmer caches on this host), i.e. "
                "per-tick overhead is statistically ZERO here, not "
                "clamped away."
            )
        elif bnd:
            fit += (
                " The fit sits on a boundary of the constrained "
                "(non-negative) model - unconstrained optimum "
                f"(c={bnd.get('per_layer_s_unconstrained')}s, "
                f"o={bnd.get('per_tick_overhead_s_unconstrained')}s); "
                "read the constrained parameters as the physical fit."
            )
        out += ["", (r.get("note", "") + fit).strip(), ""]

    sc = [r for r in rows if r.get("id", "").startswith("cnn_dp_scaling")
          and "points" in r]
    if sc:
        r = sc[-1]
        out += [
            "## Data-parallel scaling shape - "
            f"{r['devices']}-device {r['platform']} mesh, "
            f"{r['host_cores']} host core(s)",
            "",
            "The reference's Table 1 sweep (fixed 50k-row dataset, more "
            "workers) re-run on the virtual mesh: fixed total work, mesh "
            "size n swept, per-epoch (unfused) path so the sync phase is "
            "attributable (`train/measure.py measure_dp_scaling`). On "
            "shared host cores ideal wall-clock is FLAT in n, so "
            "`overhead vs n=1` isolates the parallelization + sync cost "
            "the reference pays 375 s -> 1642 s for (BASELINE.md "
            "Table 1); real n-chip wall-clock divides by n modulo this "
            "curve.",
            "",
            fmt_row(["mesh n", "train+sync s", "sync s", "sync %",
                     "overhead vs n=1"]),
            fmt_row(["---"] * 5),
        ]
        for c in r["points"]:
            out.append(fmt_row([
                c["n"], c["train_s"], c["sync_phase_s"],
                f"{100 * c['sync_frac']:.2f}%", c["overhead_vs_n1"],
            ]))
        out += ["", r.get("note", ""), ""]

    sp_rows = [r for r in rows if "_sp_scaling_" in r.get("id", "")
               and "points" in r]
    for r in sp_rows:
        impl = r.get("attn_impl", "ring")
        out += [
            f"## Sequence-parallel scaling shape - {impl} attention, "
            f"{r['devices']}-device {r['platform']} mesh, "
            f"{r['host_cores']} host core(s)",
            "",
            "Long-context evidence within a one-chip environment: fixed "
            f"global sequence ({r['seq_len']} tokens, "
            f"d{r['d_model']}/L{r['n_layers']} LM), sp swept - each "
            "device holds seq/sp tokens and "
            + ("ring attention rotates K/V blocks sp-1 times per layer"
               if impl in ("ring", "zigzag") else
               "Ulysses re-shards heads<->sequence with one all_to_all "
               "each way per attention")
            + " (`parallel/ring.py`; "
            "`train/measure.py measure_sp_scaling`). Total FLOPs are "
            "identical at every sp on the shared host core, so ideal "
            "wall is flat and `overhead vs sp=1` is the measured "
            "sequence-parallel cost; real sp-chip wall divides by sp "
            "modulo this curve.",
            "",
            fmt_row(["sp", "wall s", "tokens/s", "loss",
                     "overhead vs sp=1"]),
            fmt_row(["---"] * 5),
        ]
        for c in r["points"]:
            out.append(fmt_row([
                c["sp"], c["wall_s"], f"{c['tokens_per_s']:,}",
                c["final_loss"], c["overhead_vs_sp1"],
            ]))
        out += [
            "",
            "The identical loss column is the semantics check: every sp "
            "computes the same model step.",
            "",
        ]
        if any(c["overhead_vs_sp1"] < 1.0 for c in r["points"]):
            mech = (
                "the sharded path works the scores in (S/sp)-tile K/V "
                "blocks that fit cache"
                if impl in ("ring", "zigzag") else
                "the sharded path attends heads/sp heads per device at "
                "a time, shrinking the live working set"
            )
            out += [
                "Cells < 1 are real on this host: the sp=1 baseline "
                "materializes the full (S, S) score matrix for every "
                f"head at once, while {mech} - locality outweighing "
                "the collective cost on a shared core. On real chips "
                "the same locality shows up inside flash attention "
                "instead, and the collectives ride ICI.",
                "",
            ]
        if impl == "zigzag":
            # the comparative claim is DERIVED from the sibling rows at
            # render time, never hardcoded: host noise has swung these
            # curves before, and prose must not outlive its data
            def _ov(which):
                row = next((x for x in sp_rows
                            if x.get("attn_impl") == which), None)
                return ({p["sp"]: p["overhead_vs_sp1"]
                         for p in row["points"]} if row else {})

            zig, ring_o, uly = _ov("zigzag"), _ov("ring"), _ov("ulysses")
            comp_sps = [s for s in zig
                        if s >= 2 and s in ring_o and s in uly]
            beats = bool(comp_sps) and all(
                zig[s] < min(ring_o[s], uly[s]) for s in comp_sps)
            out += [
                "Zigzag is the load-balanced causal ring: each device "
                "holds a (front, back) slice pair (`parallel/ring.py "
                "zigzag_order`), so causal work is even across the ring "
                "instead of early shards sitting nearly idle."
                + (" In the rows above it sits below both plain ring "
                   "and Ulysses at every measured sp >= 2 - the "
                   "load-balance claim, measured." if beats else "")
                + " Tokens are fed "
                "in zigzag shard order (the caller permutes; the sweep "
                "does this per sp - without it each point trains a "
                "differently-permuted objective and the loss column "
                "drifts, which is exactly how a missing permute was "
                "caught in round 5).",
                "",
            ]
        if impl == "ulysses":
            out += [
                "History: the r4 measurement of this row showed a 2x "
                "cliff exactly at sp=8 (overhead 1.923 after 0.897 at "
                "sp=4) - the H == sp boundary where each device holds "
                "ONE head. A component ablation "
                "(`tools/diagnose_ulysses.py`, artifact "
                "`tools/ulysses_diag.json`) isolated it: the four "
                "all_to_alls stay flat (~14 -> ~27 ms from sp=2 to "
                "sp=8) while the LOCAL attention alone reproduced the "
                "blow-up, and the artifact's mesh-free contrast shows "
                "the size-1-head 4-D einsum running SLOWER than the "
                "2-head case despite HALF the FLOPs (494 vs 422 ms "
                "fwd+bwd), where proper FLOP scaling predicts ~2x "
                "faster - an XLA:CPU lowering pathology, not a Ulysses "
                "cost. Fix: `parallel/ring.py attention()` routes "
                "H == 1 through an equivalent squeezed 3-D contraction "
                "(189 ms on the same shape, 2.6x; numerics pinned by "
                "`tests/test_ring.py`); the re-measured sp=8 cell "
                "above now sits at the curve's minimum.",
                "",
            ]

    epr = [r for r in rows if "_ep_scaling_" in r.get("id", "")
           and "points" in r]
    if epr:
        r = epr[-1]
        out += [
            f"## Expert-parallel scaling shape - {r['n_experts']} "
            f"experts, top-{r['top_k']}, {r['devices']}-device "
            f"{r['platform']} mesh, {r['host_cores']} host core(s)",
            "",
            "The EP analog of the dp/sp rows: fixed global batch and "
            f"data (d{r['d_model']}/L{r['n_layers']} MoE LM, "
            f"seq {r['seq_len']}), expert axis swept - experts shard "
            "over the data axis (`train/lm.py`), each MoE layer paying "
            "one all_to_all each way at ep>1 and none at ep=1 "
            "(`parallel/moe.py`; `train/measure.py measure_ep_scaling`). "
            "No-drop capacity (factor = E/top_k) makes every ep compute "
            "the same step, so the loss column agrees to "
            "blockwise-reduction tolerance.",
            "",
            fmt_row(["ep", "experts/device", "wall s", "tokens/s",
                     "loss", "overhead vs ep=1"]),
            fmt_row(["---"] * 6),
        ]
        for c in r["points"]:
            out.append(fmt_row([
                c["ep"], c["experts_per_device"], c["wall_s"],
                f"{c['tokens_per_s']:,}", c["final_loss"],
                c["overhead_vs_ep1"],
            ]))
        out += [""]

    zm = [r for r in rows if r.get("id", "").startswith("zero1_")
          and "optimizers" in r]
    if zm:
        r = zm[-1]
        opts = r["optimizers"]

        def mb(b):
            return f"{b / 1e6:.2f} MB"

        out += [
            "## ZeRO-1 optimizer-state footprint - measured device "
            "buffers",
            "",
            f"Committed per-device buffer bytes (`addressable_shards`) "
            f"for a d{r['d_model']}/L{r['n_layers']} LM "
            f"({r['n_params']:,} params, {mb(r['param_bytes_per_device'])}"
            f" of parameters per device) on a {r['devices']}-device "
            f"{r['platform']} mesh - counted at init and again after one "
            "compiled train step, so the artifact proves the state stays "
            "sharded through the jitted update "
            "(`train/measure.py measure_zero_memory`). The reference's "
            "per-worker private optimizers multiply this memory with "
            "worker count (`data_parallelism_train.py:187`); ZeRO-1 "
            "divides it.",
            "",
            fmt_row(["optimizer", "state MB/device (init)",
                     "after 1 step", "loss after 1 step"]),
            fmt_row(["---"] * 4),
        ]
        for name, o in opts.items():
            out.append(fmt_row([
                name, mb(o["state_bytes_per_device"]),
                mb(o["state_bytes_per_device_post_step"]),
                o["final_loss"],
            ]))
        red = r.get("reduction_x")
        exp = r.get("expected_zero_bytes_per_device")
        zb = opts.get("zero-adam", {}).get("state_bytes_per_device")
        exact = (" - byte-exact vs the derived per-leaf shard layout"
                 if zb == exp else "")
        out += [
            "",
            f"Measured reduction: **{red}x** per device{exact}; the "
            "identical loss is the semantics check (ZeRO-1 partitions "
            "state, not math - `tests/test_zero.py`).",
            "",
        ]

    nb = [r for r in rows if r.get("id", "").startswith("native_batcher")
          and "kernels" in r]
    if nb:
        r = nb[-1]
        out += [
            "## Native host kernels - C++ batcher vs its numpy fallback",
            "",
            "The runtime around the XLA compute path is native where the "
            "host input pipeline is hot (`native/batcher.cpp`, "
            "build-on-import + ctypes). Best-of-"
            f"{r['reps']} wall per kernel against the SAME pure-numpy "
            "fallback the wrappers ship (`native.fallback_*` - one "
            "source of truth, parity pinned by `tests/test_native.py`), "
            f"on {r['host_cores']} host core(s); no jax, no chip claim "
            "(`train/measure.py measure_native_batcher`).",
            "",
            fmt_row(["kernel", "native ms", "numpy ms", "speedup",
                     "native images/s"]),
            fmt_row(["---"] * 5),
        ]
        if not r.get("native_available"):
            out += [
                "**NOTE: the native library was unavailable when this "
                "row measured** - both columns ran the numpy fallback, "
                "so the speedups below are ~1x and price nothing; "
                "re-measure on a host with a C++ toolchain.",
                "",
            ]
        for name, k in r["kernels"].items():
            out.append(fmt_row([
                name, k["native_ms"], k["fallback_ms"],
                f"{k['speedup_x']}x", f"{k['native_images_per_s']:,}",
            ]))
        out += [""]

    ft = [r for r in rows if r.get("id", "").startswith("cnn_fault")
          and "points" in r]
    if ft:
        r = ft[-1]
        out += [
            "## Fault injection under load - the experiment the "
            "reference never ran",
            "",
            f"`--failure-probability` sweep at a fixed seed "
            f"({r['epochs']} epochs, bs {r['batch_size']}, "
            f"{r['devices']}-device {r['platform']} mesh; "
            "`train/measure.py measure_fault_tolerance`). The reference "
            "implements fault injection but published no fault numbers "
            "(its report section 6.2), and its straggler-sleep design "
            "stalls the whole epoch behind a blocking recv "
            "(`data_parallelism_train.py:227`); here a dropped device "
            "is excluded from the epoch-edge average by the live-mask "
            "(`parallel/fault.py`) and nobody waits.",
            "",
            fmt_row(["failure p", "val acc %", "val loss",
                     "mean live frac", "epochs degraded",
                     "wall vs p=0"]),
            fmt_row(["---"] * 6),
        ]
        # a custom sweep without a p=0 control carries wall_vs_p0=None
        # (+ wall_vs_first); render the ratio that actually exists
        has_p0 = all(c["wall_vs_p0"] is not None for c in r["points"])
        for c in r["points"]:
            out.append(fmt_row([
                c["failure_probability"], c["val_acc"], c["val_loss"],
                c["mean_live_frac"], c["epochs_degraded"],
                c["wall_vs_p0"] if has_p0
                else f"{c.get('wall_vs_first', '-')} (vs first point; "
                     "sweep has no p=0 control)",
            ]))
        out += [
            "",
            "Wall-clock flat in p is the drop-and-continue claim; "
            "accuracy holding at the control's level while only "
            f"{min(c['mean_live_frac'] for c in r['points']):.0%} of "
            "epoch contributions survive is the convergence-robustness "
            "claim"
            + (" (same seed: p=0 is the exact control)." if has_p0 else
               " (custom sweep: no p=0 control; ratios are vs the "
               "sweep's first point)."),
            "",
        ]
        st = r.get("straggler")
        if st:
            out += [
                "The reference's straggler semantics, priced: with "
                f"`--failure-duration {st['duration_s']}` at "
                f"p={st['failure_probability']} (same seed, identical "
                "masks and compute, per-epoch path, duration 0 vs "
                f"{st['duration_s']}), {st['epochs_degraded']} degraded "
                f"epochs predict a {st['predicted_stall_s']} s stall and "
                f"measure {st['measured_stall_s']} s - wall-clock the "
                "fused drop-and-continue path never pays.",
                "",
            ]
    return out


def _flash_tune_sections() -> list[str]:
    """Per-pass flash-attention ablation from tools/flash_tune_*.json.

    The r3 MFU diagnosis located the end-to-end gap in the attention
    backward pass; this renders the hardware evidence (fwd-only and
    fwd+bwd wall-clock per implementation, with attention-TFLOP/s) so the
    ceiling argument is a table in the artifact, not a memory. Files are
    written by tools/tune_flash.py under honest value-fetch fencing."""
    import glob

    out = []
    paths = sorted(glob.glob(os.path.join(REPO, "tools", "flash_tune_*.json")))
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        abl = data.get("ablation")
        shape = data.get("shape", {})
        if not abl:
            continue
        if not out:
            out += [
                "## Flash-attention kernel ablation - per-pass, measured",
                "",
                "Hard-fenced kernel microbenchmarks (`tools/tune_flash.py`,"
                " 20-step mean after warm-up). `own` = this framework's"
                " vma-typed Pallas kernels (`ops/flash_pallas.py`) at their"
                " best swept blocks; `lib` = the kernel shipped with JAX at"
                " its best uniform blocks; `xla` = fused plain attention."
                " bwd is derived (fwd+bwd minus fwd at the same forward"
                " config). TFLOP/s uses causal attention FLOPs"
                " (2*B*H*S^2*D fwd; 2.5x that bwd).",
                "",
            ]
        b, h = shape.get("batch"), shape.get("heads")
        s, d = shape.get("seq"), shape.get("head_dim")
        out += [
            f"### B{b} x H{h} x S{s} x Dh{d} ({data.get('device')}, "
            "bf16)",
            "",
        ]
        def _unmeasured(a):
            # an impl whose ms timings all failed or never ran; shared
            # by the note and (implicitly) the all-dash table rows so
            # the two cannot disagree
            return not a or all(a.get(k) is None
                                for k in ("fwd_ms", "fwdbwd_ms"))

        if data.get("recovered_from_log"):
            missing = [n for n in ("own", "lib", "xla")
                       if _unmeasured(abl.get(n))]
            gap = (f" Implementations the sweep never reached: "
                   f"{', '.join(missing)}." if missing else "")
            out += [
                "Recovered from the measurement-session log "
                "(`tools/recover_tune.py`): the tunnel died mid-sweep, "
                "so rows past that point were never re-measured - "
                "missing cells are `-`, not zero. The ms timings are "
                "direct hard-fenced measurements; bwd and TFLOP/s are "
                f"derived from them as the intro above states.{gap}",
                "",
            ]
        out += [
            fmt_row(["impl", "fwd ms", "bwd ms", "fwd+bwd ms",
                     "fwd TFLOP/s", "bwd TFLOP/s"]),
            fmt_row(["---"] * 6),
        ]

        def _cell(v):
            return "-" if v is None else v

        suspect = []
        for name in ("own", "lib", "xla"):
            a = abl.get(name)
            if not a:
                continue
            # an all-dash row (every config of this impl errored) stays
            # visible rather than silently vanishing from the sweep
            out.append(fmt_row([
                name,
                _cell(a.get("fwd_ms")), _cell(a.get("bwd_ms_derived")),
                _cell(a.get("fwdbwd_ms")),
                _cell(a.get("fwd_attn_tflops_per_s")),
                _cell(a.get("bwd_attn_tflops_per_s")),
            ]))
            # a derived-bwd rate at/above the chip's peak is arithmetic
            # proof that the paired fwd-only timing overstates the fwd
            # cost inside the fwd+bwd program (different fusion/layout,
            # or unsubtracted fence RTT in older tune files) - flag it
            # rather than publish an impossible number. Peak is looked
            # up for the file's recorded device (tune files write the
            # kind with underscores)
            from distributed_neural_network_tpu.train.measure import (
                peak_flops,
            )

            kind = str(data.get("device", "")).replace("_", " ")
            peak = peak_flops(kind, "bfloat16")
            peak_tf = peak / 1e12 if peak else None
            bwd_tf = a.get("bwd_attn_tflops_per_s")
            # the tune's TFLOP/s convention credits HALVED causal FLOPs
            # (tools/tune_flash.py: fwd = 2*B*H*S^2*D, the work a
            # causal-skipping kernel actually executes), so even a
            # perfect skipping kernel tops out at 1x the hardware peak
            # (a non-skipping kernel at <=0.5x) - at/above peak the
            # split is arithmetically impossible
            if (peak_tf is not None
                    and isinstance(bwd_tf, (int, float))
                    and bwd_tf >= peak_tf):
                suspect.append(name)
        if suspect:
            out += [
                "",
                f"NOTE: derived bwd TFLOP/s for {', '.join(suspect)} "
                "meets/exceeds this device's bf16 peak "
                f"({peak_tf:.0f}) - impossible even with causal "
                "skipping (the convention already credits only the "
                "halved causal FLOPs), so the fwd/bwd SPLIT for that "
                "impl is unreliable (the standalone fwd timing does not "
                "match the fwd embedded in the fwd+bwd program); the "
                "fwd+bwd column remains a direct measurement.",
            ]
        best = data.get("best_own")
        if best:
            out += [
                "",
                "best own blocks: "
                f"fwd ({best['bq']}, {best['bk']}), "
                f"dq ({best['bq_dq']}, {best['bk_dq']}), "
                f"dkv ({best['bq_dkv']}, {best['bk_dkv']}) - loaded "
                "automatically at matching shapes "
                "(`ops/flash.py tuned_blocks`).",
                "",
            ]
    return out


if __name__ == "__main__":
    sys.exit(main())
