#!/usr/bin/env python
"""Train the transformer LM with any mesh factorization from the CLI.

The reference has exactly one model (the CIFAR CNN) and one parallelism
axis; this entry point exposes the framework's multi-axis portfolio -
data / sequence (ring or Ulysses attention) / tensor / expert parallelism
and the ZeRO-1 sharded optimizer - on a dp x sp x tp mesh, or pipeline
parallelism on a dp x pp x tp mesh. The task is the built-in synthetic
copy task (second half of each sequence repeats the first), so convergence
is observable without a corpus: loss should fall toward ~0.

Examples (8 devices - real or XLA_FLAGS=--xla_force_host_platform_device_count=8):
  python lm_train.py --dp 2 --sp 2 --tp 2 --attn ring --steps 100
  python lm_train.py --dp 8 --optimizer zero --steps 100
  python lm_train.py --dp 4 --tp 2 --experts 8 --steps 100
  python lm_train.py --pp 4 --dp 2 --microbatches 2 --steps 100
  python lm_train.py --dp 2 --sp 4 --attn ulysses --seq-len 512 --steps 50
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# checkpoint momentum-layout version: "tree" = per-leaf momentum trees for
# both sgd and zero (the round-2 layout); bump on any layout change so
# resume rejects old checkpoints with a clear message
MOM_FORMAT = "tree"

def main() -> int:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter
    )
    p.add_argument("--dp", type=int, default=1, help="data-parallel axis size")
    p.add_argument("--sp", type=int, default=1, help="sequence-parallel axis size")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel axis size")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline stages (uses the dp x pp x tp mesh; "
                   "exclusive with --sp; composes with --experts (experts "
                   "shard over dp); zero optimizers compose with --dp, "
                   "not --tp/--experts)")
    p.add_argument("--sharding", default="manual", metavar="MODE",
                   help="how the partition layout is chosen (dp x sp x tp "
                   "mesh path): 'manual' (default) shards per "
                   "--dp/--sp/--tp with the built-in partition-rule table "
                   "(parallel/rules.py); 'auto' runs the static cost-model "
                   "search (analysis/autoshard.py) over every mesh "
                   "factorization of --dp*--sp*--tp devices (or all "
                   "visible devices when those are 1) and adopts the "
                   "winning plan - pure abstract tracing, nothing "
                   "executes; 'rules:<file>' loads a custom ordered "
                   "[regex, spec] JSON rule list for the param layout "
                   "(every leaf must match)")
    p.add_argument("--microbatches", type=int, default=2)
    p.add_argument(
        "--pp-interleave", type=int, default=1,
        help="virtual pipeline stages per device (circular schedule): "
        "cuts the bubble from (P-1)/(M+P-1) to (P-1)/(v*M+P-1) at the "
        "cost of v-times-finer layer chunks; needs pp*v | layers and "
        "pp | microbatches",
    )
    p.add_argument(
        "--attn", choices=("ring", "ulysses", "zigzag", "flash"),
        default="ring",
        help="sequence-parallel attention; zigzag = load-balanced causal "
        "ring (~2x ring's causal throughput; tokens are fed in zigzag "
        "shard order automatically); flash = Pallas TPU kernel for the "
        "local sp=1 case",
    )
    p.add_argument("--experts", type=int, default=0,
                   help="MoE expert count (0 = dense FFN)")
    p.add_argument(
        "--optimizer", choices=("sgd", "adam", "zero", "zero-adam"),
        default="sgd",
        help="sgd/adam = replicated state; zero/zero-adam = ZeRO-1 state "
        "sharded over the data axis (adam state is 2x params, so sharding "
        "it saves the most)",
    )
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--stop-at-step", type=int, default=None, metavar="N",
                   help="ABSOLUTE step to stop before (end_step = N), "
                   "overriding the relative '--steps more' semantics on "
                   "resume - the supervisor (tools/launch.py) passes this "
                   "so every relaunch of an elastic group trains to the "
                   "same target instead of adding --steps per restart")
    p.add_argument("--batch-size", type=int, default=32, help="global batch")
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-layers", type=int, default=4)
    p.add_argument("--d-ff", type=int, default=512)
    p.add_argument("--dtype", choices=("float32", "bfloat16"), default="float32")
    p.add_argument(
        "--precision", choices=("bf16", "fp8", "int8", "int8-kv"),
        default="bf16",
        help="low-precision fast path (ops/quant.py): 'fp8'/'int8' run "
        "the attention QK^T/PV matmuls quantized with per-token scales "
        "and wide accumulation (forward only - backward stays full "
        "precision; the bench parity row gates the loss/logit drift, "
        "docs/MEASUREMENT.md); 'bf16' (default) is the unquantized "
        "path ('bf16' names the ACCUMULATION contract, not --dtype). "
        "'int8-kv' is the serving-side KV-cache quantization - use "
        "python -m distributed_neural_network_tpu.serve --precision "
        "int8-kv",
    )
    p.add_argument("--loss-chunks", type=int, default=0,
                   help="compute the CE loss in this many sequence chunks "
                   "so full (B, S, vocab) logits never materialize "
                   "(0 = auto-pick by a 64 MB logits budget, 1 = single pass)")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize blocks in backward (jax.checkpoint): "
                   "~1/3 more FLOPs for far less activation memory")
    p.add_argument("--remat-policy", default="",
                   help="jax.checkpoint_policies name applied with --remat "
                   "(e.g. dots_saveable: store matmul outputs, recompute "
                   "only elementwise - a few percent FLOP tax instead of "
                   "full remat's ~1/3); '' = save nothing")
    p.add_argument("--remat-attn", action="store_true",
                   help="rematerialize ONLY the attention scores/softmax in "
                   "backward: avoids storing the (B,H,S,S) tensor for a few "
                   "percent extra FLOPs - the cheap alternative to --remat "
                   "for the XLA attention path (no-op with --remat)")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--lr-schedule", choices=("constant", "cosine"),
                   default="constant",
                   help="cosine = linear warmup (--warmup-steps) then "
                   "half-cosine decay over --steps to --min-lr-frac * lr")
    p.add_argument("--warmup-steps", type=int, default=0)
    p.add_argument("--min-lr-frac", type=float, default=0.0,
                   help="cosine floor as a fraction of --lr")
    p.add_argument("--clip-norm", type=float, default=0.0,
                   help="clip gradients to this global L2 norm before the "
                   "optimizer (0 = off); sharding-aware across dp/sp/tp/pp")
    p.add_argument("--accum-steps", type=int, default=1,
                   help="gradient accumulation: scan this many sequential "
                   "fwd/bwd micro-batches per optimizer step (batch-size "
                   "must divide by dp * accum-steps; under --pp also by "
                   "microbatches per pass - prefer raising --microbatches "
                   "until activation memory binds, then accumulate)")
    p.add_argument("--grad-sync", choices=("end", "overlap"), default="end",
                   help="gradient-sync schedule under --accum-steps k>1: "
                   "end = one bulk sync after the accumulation scan "
                   "(existing behavior); overlap = one collective per "
                   "size-capped leaf bucket (--bucket-mb) PER MICROBATCH "
                   "inside the scan, so the interconnect works while the "
                   "next microbatch's backward runs - with zero/zero-adam "
                   "the scan carries only this device's 1/dp gradient "
                   "shard (reduce-scatter), shrinking the accumulator "
                   "from O(D) to O(D/dp). Same result up to float "
                   "reassociation; identical at --accum-steps 1. Not "
                   "compatible with --experts at dp>1")
    p.add_argument("--bucket-mb", type=float, default=4.0,
                   help="gradient-bucket payload cap in MiB for "
                   "--grad-sync overlap")
    p.add_argument("--compilation-cache-dir", default=None,
                   help="persistent XLA compilation cache dir "
                   "(jax_compilation_cache_dir): repeat runs deserialize "
                   "instead of recompiling; the --step-stats compile "
                   "field then shows the cache-hit time")
    p.add_argument("--ema-decay", type=float, default=0.0,
                   help="track an exponential moving average of params "
                   "(e.g. 0.999) and use it for --eval-every/--generate; "
                   "0 = off. Not checkpointed: resume restarts the average "
                   "from the restored params")
    p.add_argument("--weight-decay", type=float, default=0.0,
                   help="decoupled (AdamW-style) weight decay; applied by "
                   "every optimizer on both the mesh and pipeline paths")
    p.add_argument("--momentum", type=float, default=0.9,
                   help="SGD momentum; for adam/zero-adam this is b1 "
                   "(the first-moment decay, Adam's momentum analog)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--data-path", default=None,
                   help="token corpus (.npy, raw .bin of uint16 tokens, or "
                   ".txt byte-tokenized as uint8 - one flat stream): each "
                   "step samples fresh (B, S) windows; default = the fixed "
                   "synthetic copy-task batch")
    p.add_argument("--eval-every", type=int, default=0,
                   help="every N steps report held-out loss/perplexity "
                   "over --eval-batches windows (requires --data-path; "
                   "the stream tail is the eval split)")
    p.add_argument("--eval-batches", type=int, default=8)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--metrics-jsonl", default=None,
                   help="append train/loss (+ val/loss on --eval-every) "
                   "series to this JSONL file - the reference's metric "
                   "channel (utils/metrics.py), shared with the CNN engine")
    p.add_argument("--run-record", default=None, metavar="RECORD.json",
                   help="write the goodput run record here (wall-clock "
                   "efficiency accounting, utils/goodput.py: goodput "
                   "ratio + per-cause badput seconds, config fingerprint, "
                   "mesh, step/token counts; written through during the "
                   "run so even a SIGKILL leaves the accounting on disk; "
                   "render/diff/gate with tools/goodput.py). Defaults to "
                   "the DNN_TPU_RUN_RECORD env the elastic supervisor "
                   "exports; the breakdown is always printed as a "
                   "GOODPUT line either way")
    p.add_argument("--trace-out", default=None, metavar="TRACE.json",
                   help="write a Chrome trace-event JSON of the run (one "
                   "train_step span per step, fenced - adds one scalar "
                   "device fetch per step); open in Perfetto or summarize "
                   "with tools/trace_summary.py (docs/OBSERVABILITY.md)")
    p.add_argument("--step-stats", action="store_true",
                   help="collect per-step StepStats (compile vs steady "
                   "step time, tokens/s, device memory, collective bytes, "
                   "MFU from cost_analysis with analytic fallback), print "
                   "the summary, and emit step/* series to --metrics-jsonl")
    p.add_argument("--dynamics", action="store_true",
                   help="training-dynamics telemetry (train/dynamics.py, "
                   "docs/OBSERVABILITY.md): the compiled step emits one "
                   "extra mesh-reduced bundle - per-layer grad/param/"
                   "update-to-weight norms, the gradient-noise scale "
                   "(with --accum-steps >= 2 and --grad-sync end), and "
                   "the first non-finite layer index for provenance - "
                   "decoded one step behind like the guard's health "
                   "bundle; streams to --dynamics-jsonl, dynamics_* "
                   "gauges, and the 'dynamics' trace track. Mesh path "
                   "only (not --pp)")
    p.add_argument("--dynamics-jsonl", default=None, metavar="DYN.jsonl",
                   help="append the per-step dynamics rows here (one JSON "
                   "object per step: global + per-layer norms, GNS "
                   "readout, bad_layer); render/diff/gate with "
                   "tools/dynamics.py")
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve live Prometheus metrics on http://127.0.0.1"
                   ":PORT/metrics plus a /healthz JSON liveness/readiness "
                   "endpoint (0 = ephemeral port, printed at startup); "
                   "also starts the stall/recompile/checkpoint watchdog "
                   "unless --watchdog off (utils/obs.py, train/monitor.py, "
                   "docs/OBSERVABILITY.md; watch live with "
                   "tools/live_top.py http://127.0.0.1:PORT)")
    p.add_argument("--metrics-linger", type=float, default=0.0,
                   metavar="SEC",
                   help="keep the metrics server up this many seconds "
                   "after the run finishes (final scrape window)")
    p.add_argument("--profile-dir", default=None, metavar="DIR",
                   help="with --metrics-port: serve /profile?steps=N - "
                   "an on-demand jax.profiler capture of the next N "
                   "steps, written under DIR (default: next to "
                   "--trace-out when set; without either the endpoint "
                   "answers 501)")
    p.add_argument("--watchdog", choices=("on", "off"), default="on",
                   help="with --metrics-port: background watchdog flagging "
                   "stalled steps (no heartbeat for N x steady p95 step "
                   "time), recompile storms, and checkpoint staleness as "
                   "watchdog/* trace events + watchdog_*_total counters")
    p.add_argument("--watchdog-escalate", choices=("none", "preempt"),
                   default="none",
                   help="preempt = a persistent stall requests the "
                   "cooperative preemption path (emergency checkpoint at "
                   "the next step boundary, clean exit); requires "
                   "--on-sigterm checkpoint")
    p.add_argument("--checkpoint-dir", default=None,
                   help="save params+momentum every --checkpoint-every steps")
    p.add_argument("--checkpoint-every", type=int, default=50)
    p.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in --checkpoint-dir")
    p.add_argument("--elastic", action="store_true",
                   help="elastic resume (parallel/reshard.py, docs/"
                   "ROBUSTNESS.md): accept a checkpoint saved under a "
                   "DIFFERENT mesh shape or optimizer layout and reshard "
                   "it onto this run's mesh - dp/sp/tp may all change, "
                   "ZeRO shards re-pad for the new dp, and sgd<->zero / "
                   "adam<->zero-adam convert bitwise; the global batch "
                   "stays fixed (grad accumulation is re-sliced) so the "
                   "exact-resume data cursor still holds")
    p.add_argument("--guard", choices=("off", "warn", "skip", "rollback",
                                       "abort"),
                   default="off",
                   help="self-healing step guard (train/guard.py, "
                   "docs/ROBUSTNESS.md): the compiled step emits a health "
                   "bundle (loss, global grad-norm, all-finite flag) "
                   "observed one step behind the dispatch pipeline. "
                   "warn = count/log anomalies; skip = additionally drop "
                   "non-finite updates INSIDE the compiled step (params/"
                   "momentum pass through unchanged); rollback = restore "
                   "the rolling in-memory snapshot (or newest checkpoint) "
                   "and retry with LR backoff; abort = stop with an "
                   "actionable error. Mesh path only (not --pp)")
    p.add_argument("--guard-spike-zscore", type=float, default=6.0,
                   help="loss-spike threshold in EMA standard deviations; "
                   "non-finite steps always count as anomalies")
    p.add_argument("--snapshot-every", type=int, default=50,
                   help="steps between the guard's rolling host snapshots "
                   "(one device_get of params+momentum each; a rollback "
                   "rewinds at most this many steps)")
    p.add_argument("--max-retries", type=int, default=3,
                   help="guard rollback budget before abort (refills after "
                   "a stretch of healthy steps)")
    p.add_argument("--on-sigterm", choices=("checkpoint", "ignore"),
                   default="checkpoint",
                   help="checkpoint = on SIGTERM/SIGINT finish the current "
                   "step, write an emergency checkpoint (when "
                   "--checkpoint-dir is set) and exit cleanly; resume "
                   "replays from the exact batch, bit-identical. "
                   "ignore = default signal behavior")
    p.add_argument("--chaos-nan-step", type=int, action="append",
                   default=None, metavar="N",
                   help="fault injection (parallel/fault.py): NaN the "
                   "gradient tree at step N inside the compiled step "
                   "(repeatable); exercises the guard's in-jit skip path")
    p.add_argument("--chaos-nan-layer", default=None, metavar="REGEX",
                   help="restrict --chaos-nan-step to gradient leaves whose "
                   "/-joined tree path matches this regex (parallel/"
                   "fault.py nan_layer; e.g. 'blocks/3/.*'): with "
                   "--dynamics the non-finite provenance must name one of "
                   "the matched layers in the guard anomaly, flight "
                   "recorder, and postmortem")
    p.add_argument("--chaos-spike-step", type=int, action="append",
                   default=None, metavar="N",
                   help="fault injection: multiply the OBSERVED loss at "
                   "step N by 100 (host-side, fires once, so a rollback "
                   "replay sees a healthy step)")
    p.add_argument("--chaos-sigterm-after", type=int, default=None,
                   metavar="N",
                   help="fault injection: deliver a real SIGTERM to this "
                   "process after step N completes (drives the emergency-"
                   "checkpoint -> exact-resume path end to end)")
    p.add_argument("--chaos-stall-step", type=int, action="append",
                   default=None, metavar="N",
                   help="fault injection: sleep --chaos-stall-seconds "
                   "inside the host step callback after step N completes "
                   "(repeatable; host-side, works under --pp too) - the "
                   "heartbeat stops, which the --metrics-port watchdog "
                   "must flag as a watchdog/stall event within one "
                   "detection window")
    p.add_argument("--chaos-stall-seconds", type=float, default=2.0,
                   metavar="SEC",
                   help="stall duration for --chaos-stall-step")
    p.add_argument("--chaos-stall-rank", type=int, default=None,
                   metavar="R",
                   help="restrict --chaos-stall-step to process rank R "
                   "of a multi-process group (every rank runs the same "
                   "argv under tools/launch.py, so without this the "
                   "whole fleet stalls in lockstep); single-process runs "
                   "treat their rank as 0. Drives the supervisor's "
                   "straggler attribution validation "
                   "(fleet_straggler_rank)")
    p.add_argument("--chaos-shrink-at-step", type=int, default=None,
                   metavar="N",
                   help="fault injection (parallel/fault.py): after step N "
                   "raise a cooperative SHRINK preemption - the elastic "
                   "driver writes an emergency checkpoint, rebuilds the "
                   "mesh from the first --chaos-shrink-to devices, "
                   "reshards params+optimizer state onto it "
                   "(parallel/reshard.py) and CONTINUES training in this "
                   "process: the full preempt -> checkpoint -> reshard -> "
                   "resume path. Requires --checkpoint-dir and "
                   "--on-sigterm checkpoint; mesh path only (not --pp)")
    p.add_argument("--chaos-shrink-to", type=int, default=None,
                   metavar="DP",
                   help="data-parallel size the SHRINK preemption drops to "
                   "(default dp//2); sp/tp are kept, the global batch is "
                   "preserved by re-slicing gradient accumulation")
    p.add_argument("--gen-temperature", type=float, default=0.0,
                   help="sampling temperature for --generate (0 = greedy)")
    p.add_argument("--gen-top-k", type=int, default=0,
                   help="restrict --generate sampling to the k most likely "
                   "tokens (0 = no restriction)")
    p.add_argument("--gen-top-p", type=float, default=0.0,
                   help="nucleus sampling for --generate: restrict to the "
                   "smallest token set with cumulative probability >= p "
                   "(0 = no restriction; composes after --gen-top-k)")
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, greedy-decode N tokens from the "
                   "first sequences' prompts through the KV-cache path and "
                   "print prompt/completion pairs (single-device decode)")
    args = p.parse_args()
    if args.steps < 1:
        p.error("--steps must be >= 1")
    if args.checkpoint_every < 1:
        p.error("--checkpoint-every must be >= 1")
    if args.resume and not args.checkpoint_dir:
        p.error("--resume requires --checkpoint-dir")
    if args.remat_policy and not args.remat:
        p.error("--remat-policy only applies with --remat (the policy "
                "picks WHAT checkpointed blocks save); the name is "
                "validated against jax.checkpoint_policies after startup")
    if args.eval_every and not args.data_path:
        p.error("--eval-every requires --data-path (the held-out split "
                "is the token stream's tail)")
    if args.gen_top_k and args.gen_temperature <= 0:
        p.error("--gen-top-k only applies when sampling; set "
                "--gen-temperature > 0 (temperature 0 is greedy and "
                "ignores top-k)")
    if args.gen_temperature < 0:
        p.error(f"--gen-temperature must be >= 0, got "
                f"{args.gen_temperature}")
    if not 0.0 <= args.gen_top_p <= 1.0:
        p.error(f"--gen-top-p must be in [0, 1], got {args.gen_top_p}")
    if args.gen_top_p and args.gen_temperature <= 0:
        p.error("--gen-top-p only applies when sampling; set "
                "--gen-temperature > 0 (temperature 0 is greedy and "
                "ignores top-p)")
    if args.generate <= 0 and (args.gen_temperature > 0 or args.gen_top_k
                               or args.gen_top_p):
        p.error("--gen-temperature/--gen-top-k/--gen-top-p configure "
                "--generate N, which was not requested - add "
                "--generate N or drop the sampling flags")
    if args.sharding not in ("manual", "auto") and not args.sharding.startswith(
        "rules:"
    ):
        p.error(
            f"--sharding must be 'manual', 'auto', or 'rules:<file>', got "
            f"{args.sharding!r}"
        )
    if args.sharding == "rules:":
        p.error("--sharding rules: needs a file path (rules:<file>)")
    if args.sharding != "manual" and args.pp > 1:
        p.error(
            "--sharding auto/rules:<file> drive the dp x sp x tp mesh "
            "path's partition layer (parallel/rules.py); the pipeline "
            "path's stage sharding is fixed by --pp - drop --pp or use "
            "--sharding manual"
        )
    if args.ema_decay and args.pp > 1:
        p.error("--ema-decay is unused under --pp (the pipeline path has "
                "no --eval-every/--generate consumer for the averaged "
                "weights); drop it or use the dp x sp x tp mesh")
    if args.loss_chunks > 1 and (
        args.seq_len // max(args.sp, 1)
    ) % args.loss_chunks:
        p.error(
            f"--loss-chunks {args.loss_chunks} must divide the per-shard "
            f"sequence length {args.seq_len // max(args.sp, 1)} "
            f"(--seq-len / --sp; the CE is chunked along the local "
            "sequence axis)"
        )
    if args.attn == "zigzag" and args.sp > 1 and args.seq_len % (2 * args.sp):
        p.error(
            f"--attn zigzag needs --seq-len divisible by 2*sp "
            f"({2 * args.sp}); got {args.seq_len}"
        )
    if args.attn == "flash" and args.sp > 1:
        p.error(
            "--attn flash is the local (per-device) kernel and composes "
            "with --dp/--tp (own vma-typed Pallas kernels, round 4); a "
            "sequence axis needs --attn ring/ulysses/zigzag"
        )
    if args.precision == "int8-kv":
        p.error(
            "--precision int8-kv quantizes the SERVING KV cache (paged "
            "pool + per-block scales); it is a flag of python -m "
            "distributed_neural_network_tpu.serve. Training's quantized "
            "paths are --precision fp8|int8"
        )
    if args.precision != "bf16" and args.sp > 1:
        p.error(
            f"--precision {args.precision} quantizes the LOCAL attention "
            "matmuls; a sequence axis (ring/ulysses/zigzag) has no "
            "quantized path - drop --sp or --precision"
        )
    if args.precision != "bf16" and args.pp > 1:
        p.error(
            f"--precision {args.precision} is wired through the "
            "dp x sp x tp mesh step; the pipeline path does not thread "
            "attn_quant - drop --pp or --precision"
        )
    if args.grad_sync == "overlap" and args.experts and args.dp > 1:
        p.error(
            "--grad-sync overlap psums gradient buckets over the data "
            "axis; expert-sharded leaves (--experts with --dp > 1) vary "
            "over that axis - use --grad-sync end"
        )
    if args.bucket_mb <= 0:
        p.error(f"--bucket-mb must be > 0, got {args.bucket_mb}")
    # --chaos-stall-step is deliberately NOT in this set: it is a pure
    # host-side sleep (no health bundle involved), so it works under --pp
    chaos_injected = bool(
        args.chaos_nan_step or args.chaos_spike_step
        or args.chaos_sigterm_after is not None
    )
    if args.pp > 1 and (args.guard != "off" or chaos_injected):
        p.error(
            "--guard / --chaos-* are wired through the dp x sp x tp mesh "
            "step's health bundle (train/lm.py make_lm_train_step); the "
            "pipeline path has no health output yet - drop --pp or the "
            "guard flags"
        )
    if args.chaos_stall_seconds <= 0:
        p.error(f"--chaos-stall-seconds must be > 0, got "
                f"{args.chaos_stall_seconds}")
    if args.chaos_stall_rank is not None and not args.chaos_stall_step:
        p.error("--chaos-stall-rank restricts --chaos-stall-step, which "
                "was not given")
    if args.chaos_nan_layer is not None and not args.chaos_nan_step:
        p.error("--chaos-nan-layer restricts --chaos-nan-step, which "
                "was not given")
    if args.dynamics and args.pp > 1:
        p.error("--dynamics is wired through the dp x sp x tp mesh step's "
                "telemetry bundle (train/lm.py make_lm_train_step); the "
                "pipeline path has no dynamics output - drop --pp")
    if args.dynamics_jsonl and not args.dynamics:
        p.error("--dynamics-jsonl is the sink for --dynamics, which "
                "was not given")
    if args.elastic and not args.resume and args.chaos_shrink_at_step is None:
        p.error("--elastic configures how --resume (or a SHRINK "
                "preemption) maps a checkpoint onto this mesh; add "
                "--resume with --checkpoint-dir, or --chaos-shrink-at-step")
    if args.stop_at_step is not None and args.stop_at_step < 1:
        p.error(f"--stop-at-step must be >= 1, got {args.stop_at_step}")
    if args.chaos_shrink_at_step is not None:
        if args.pp > 1:
            p.error("--chaos-shrink-at-step shrinks the dp x sp x tp mesh "
                    "in process; drop --pp")
        if not args.checkpoint_dir:
            p.error("--chaos-shrink-at-step drives the preempt -> "
                    "checkpoint -> reshard -> resume path; it requires "
                    "--checkpoint-dir")
        if args.on_sigterm != "checkpoint":
            p.error("--chaos-shrink-at-step rides the cooperative "
                    "preemption guard; it requires --on-sigterm checkpoint")
        if args.eval_every:
            p.error("--chaos-shrink-at-step cannot rebuild the --eval-every "
                    "evaluator mid-run; drop one of the two")
        if args.chaos_shrink_to is None:
            args.chaos_shrink_to = max(args.dp // 2, 1)
        if not 1 <= args.chaos_shrink_to < args.dp:
            p.error(f"--chaos-shrink-to must be in [1, dp) = "
                    f"[1, {args.dp}), got {args.chaos_shrink_to}")
        if args.batch_size % args.chaos_shrink_to:
            p.error(f"--batch-size {args.batch_size} must divide over "
                    f"--chaos-shrink-to {args.chaos_shrink_to} (the global "
                    "batch is preserved across the shrink)")
    if args.watchdog_escalate == "preempt" and args.on_sigterm != "checkpoint":
        p.error("--watchdog-escalate preempt rides the cooperative "
                "preemption path; it requires --on-sigterm checkpoint")
    if args.snapshot_every < 1:
        p.error(f"--snapshot-every must be >= 1, got {args.snapshot_every}")
    if args.max_retries < 0:
        p.error(f"--max-retries must be >= 0, got {args.max_retries}")

    # the goodput ledger's wall clock starts BEFORE the jax import and
    # distributed rendezvous so the init bucket owns them honestly
    # (utils/goodput.py; docs/OBSERVABILITY.md "Goodput accounting")
    from distributed_neural_network_tpu.utils.goodput import (
        LEDGER as G_LEDGER,
    )

    G_LEDGER.start()
    if args.run_record:
        G_LEDGER.arm(args.run_record)

    from distributed_neural_network_tpu.train.cli import (
        enable_compilation_cache,
        honor_platform_env,
    )

    honor_platform_env()
    if args.compilation_cache_dir:
        if enable_compilation_cache(args.compilation_cache_dir):
            print(f"(persistent compilation cache: "
                  f"{args.compilation_cache_dir})")
        else:
            print("(WARNING: this jax version has no persistent "
                  "compilation cache config; --compilation-cache-dir "
                  "ignored)")
            args.compilation_cache_dir = None
    import jax
    import jax.numpy as jnp

    if args.remat_policy and not hasattr(
        jax.checkpoint_policies, args.remat_policy
    ):
        raise SystemExit(
            f"--remat-policy {args.remat_policy!r} is not a "
            "jax.checkpoint_policies name"
        )

    from distributed_neural_network_tpu.models import transformer as tfm
    from distributed_neural_network_tpu.parallel import pipeline as ppl
    from distributed_neural_network_tpu.parallel.distributed import initialize
    from distributed_neural_network_tpu.train import lm as lmtrain

    initialize()
    cfg = tfm.TransformerConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_layers=args.n_layers,
        d_ff=args.d_ff,
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32,
        remat=args.remat,
        remat_attn=args.remat_attn,
        remat_policy=args.remat_policy,
        n_experts=args.experts,
        attn_quant="" if args.precision == "bf16" else args.precision,
    )
    if args.n_heads % max(args.tp, 1):
        raise SystemExit(f"--n-heads {args.n_heads} must divide by --tp {args.tp}")

    from jax.sharding import NamedSharding, PartitionSpec as P

    # --sharding: the declarative partition layer (parallel/rules.py +
    # analysis/autoshard.py). 'auto' searches every mesh factorization of
    # the device budget with the static cost model (abstract traces only
    # - scoring happens before anything is placed or compiled) and
    # rewrites --dp/--sp/--tp to the winning plan; 'rules:<file>' swaps
    # the built-in rule table for a custom one, threaded through every
    # spec-derivation site (shard_params / make_lm_train_step / the
    # elastic reshard path).
    shard_rules = None
    if args.sharding.startswith("rules:"):
        from distributed_neural_network_tpu.parallel.rules import load_rules

        rules_path = args.sharding[len("rules:"):]
        shard_rules = load_rules(rules_path)
        print(f"(sharding rules: {rules_path}, {len(shard_rules)} rule(s))")
    elif args.sharding == "auto":
        from distributed_neural_network_tpu.analysis.autoshard import (
            search_plans,
        )

        budget = args.dp * args.sp * args.tp
        if budget == 1:
            budget = jax.device_count()
        result = search_plans(
            "lm", cfg=cfg, devices=budget, batch=args.batch_size,
            seq_len=args.seq_len, optimizer=args.optimizer,
            kwargs=dict(
                accum_steps=args.accum_steps, grad_sync=args.grad_sync,
                bucket_mb=args.bucket_mb, loss_chunks=args.loss_chunks,
                attn_impl=args.attn,
            ),
            config=f"auto@{budget}dev",
        )
        if result.chosen is None:
            raise SystemExit(
                "--sharding auto found no feasible plan over "
                f"{budget} device(s):\n" + "\n".join(
                    f"  {pl.label}: {pl.infeasible_reason}"
                    for pl in result.infeasible
                )
            )
        print(result.explain(top_k=3))
        dims = result.chosen.dims
        args.dp, args.sp, args.tp = dims["dp"], dims["sp"], dims["tp"]
        print(
            f"(sharding auto: adopted mesh dp{args.dp} x sp{args.sp} x "
            f"tp{args.tp}, optimizer {result.chosen.optimizer})"
        )

    params = tfm.init_params(jax.random.key(args.seed), cfg)
    pipe = args.pp > 1
    # guard defaults for the pipeline branch (pp + guard/chaos is rejected
    # at argparse; these keep the shared loop code below uniform)
    guard_on = False
    fault_plan = None
    build_step = None
    if pipe:
        if args.sp > 1:
            raise SystemExit(
                "--pp composes with --dp/--tp/--experts and any "
                "--optimizer (zero/zero-adam shard state over dp per "
                "stage; not with --experts or --tp); --sp runs on the "
                "dp x sp x tp mesh (drop --pp)"
            )
        if args.optimizer.startswith("zero") and (
                args.tp > 1 or (args.experts and args.dp > 1)):
            raise SystemExit(
                "--pp with zero optimizers composes with --dp only "
                "(tensor- and expert-sharded leaves are out of the "
                "per-leaf ZeRO layout's scope, same rule as the mesh "
                "path; --experts with --dp 1 keeps experts replicated "
                "and is fine)"
            )
        mesh = ppl.create_pp_mesh(args.dp, args.pp, args.tp)
        params, specs = ppl.shard_pp_params(
            params, cfg, mesh, interleave=args.pp_interleave
        )
        if args.optimizer == "adam":
            from distributed_neural_network_tpu.ops.adam import init_adam

            mom = init_adam(params)
        elif args.optimizer.startswith("zero"):
            mom = ppl.init_pp_zero_state(params, specs, mesh, args.optimizer)
        else:
            from distributed_neural_network_tpu.ops.sgd import init_momentum

            mom = init_momentum(params)
        mom_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            ppl.pp_optimizer_state_specs(args.optimizer, specs),
        )
        import functools

        from distributed_neural_network_tpu.ops import schedule as sched

        pp_lr_schedule = None
        if args.lr_schedule == "cosine":
            pp_lr_schedule = functools.partial(
                sched.warmup_cosine, base_lr=args.lr,
                total_steps=args.steps, warmup_steps=args.warmup_steps,
                min_lr_frac=args.min_lr_frac,
            )
        step = ppl.make_pp_train_step(
            cfg, mesh, n_microbatches=args.microbatches,
            lr=args.lr, momentum=args.momentum,
            loss_chunks=args.loss_chunks, interleave=args.pp_interleave,
            lr_schedule=pp_lr_schedule, clip_norm=args.clip_norm,
            weight_decay=args.weight_decay, optimizer=args.optimizer,
            accum_steps=args.accum_steps, grad_sync=args.grad_sync,
            bucket_mb=args.bucket_mb,
        )
    else:
        mesh = lmtrain.create_lm_mesh(args.dp, args.sp, args.tp)
        params, specs = lmtrain.shard_params(
            params, cfg, mesh, rules=shard_rules
        )
        mom = lmtrain.init_lm_momentum(params, mesh, args.optimizer)
        mom_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            lmtrain.optimizer_state_specs(args.optimizer, specs),
        )
        import functools

        from distributed_neural_network_tpu.ops import schedule as sched

        guard_on = args.guard != "off"
        if args.chaos_nan_step:
            from distributed_neural_network_tpu.parallel.fault import (
                StepFaultPlan,
            )

            fault_plan = StepFaultPlan(
                nan_grads_at=tuple(args.chaos_nan_step),
                nan_layer=args.chaos_nan_layer,
            )

        def build_step(lr_scale: float = 1.0):
            """The compiled mesh step at `lr * lr_scale` - the guard's LR
            backoff rebuilds it (one recompile per rollback retry, bounded
            by --max-retries; the schedule's base LR scales too)."""
            lr_schedule = None
            if args.lr_schedule == "cosine":
                lr_schedule = functools.partial(
                    sched.warmup_cosine, base_lr=args.lr * lr_scale,
                    total_steps=args.steps, warmup_steps=args.warmup_steps,
                    min_lr_frac=args.min_lr_frac,
                )
            return lmtrain.make_lm_train_step(
                cfg, mesh, lr=args.lr * lr_scale, momentum=args.momentum,
                attn_impl=args.attn, optimizer=args.optimizer,
                loss_chunks=args.loss_chunks, lr_schedule=lr_schedule,
                clip_norm=args.clip_norm, accum_steps=args.accum_steps,
                weight_decay=args.weight_decay, grad_sync=args.grad_sync,
                bucket_mb=args.bucket_mb,
                with_health=guard_on,
                skip_nonfinite=args.guard == "skip",
                fault_plan=fault_plan,
                rules=shard_rules,
                dynamics=args.dynamics,
            )

        step = build_step()

    param_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)

    def place_batch(tok, tgt):
        """Host batch -> the mesh's data sharding. Single-process: the jit
        boundary places it (a no-op here keeps that path byte-identical).
        Multi-process (a supervisor group, real multi-host): each process
        uploads only its addressable slices via `distribute_host_data` -
        the compiled step's in_specs span devices this host cannot see,
        so host arrays must become global jax.Arrays BEFORE dispatch."""
        if jax.process_count() == 1:
            return tok, tgt
        import numpy as _np

        from distributed_neural_network_tpu.parallel.distributed import (
            distribute_host_data,
        )

        spec = P("data") if pipe else P("data", "seq")
        return (
            distribute_host_data(_np.asarray(tok), mesh, spec),
            distribute_host_data(_np.asarray(tgt), mesh, spec),
        )

    mesh_desc = "x".join(
        f"{k}{v}" for k, v in mesh.shape.items() if v > 1
    ) or "single"

    # run-record identity: the config fingerprint hashes everything that
    # shapes the training computation; output paths/ports are excluded so
    # the same run in a different directory fingerprints identically
    _volatile = {
        "run_record", "metrics_port", "metrics_linger", "trace_out",
        "profile_dir", "metrics_jsonl", "checkpoint_dir",
        "compilation_cache_dir", "log_every",
    }
    G_LEDGER.describe(
        config={k: v for k, v in sorted(vars(args).items())
                if k not in _volatile},
        mesh={"axes": {k: int(v) for k, v in mesh.shape.items()},
              "devices": int(mesh.devices.size), "desc": mesh_desc,
              "optimizer": args.optimizer},
    )

    # live observability (utils/obs.py + train/monitor.py): the tracer,
    # preemption guard, and --metrics-port monitor exist BEFORE the
    # checkpointer/guard/step wiring so every layer can publish into the
    # same registry (docs/OBSERVABILITY.md "Live monitoring")
    from distributed_neural_network_tpu.train import guard as G
    from distributed_neural_network_tpu.train.monitor import (
        WatchdogConfig,
        attach_monitor,
    )
    from distributed_neural_network_tpu.utils import tracing as TRC

    tracer = TRC.Tracer(enabled=bool(args.trace_out))
    # fleet identity: under a supervised / multi-process group every rank
    # runs this same argv, so the tracer stamps rank{N} process metadata
    # and --trace-out becomes a per-rank shard (trace_rank{N}.json) that
    # tools/trace_merge.py reassembles into one aligned timeline
    rank = TRC.detect_rank()
    if rank is None and jax.process_count() > 1:
        rank = jax.process_index()
    if rank is not None:
        import socket as _socket

        tracer.set_process(rank=rank, hostname=_socket.gethostname())
        if args.trace_out:
            args.trace_out = TRC.rank_trace_path(args.trace_out, rank)
            print(f"(per-rank trace shard: {args.trace_out})")
    preempt = None
    if args.on_sigterm == "checkpoint":
        preempt = G.PreemptionGuard().install()
    profile_dir = args.profile_dir or (
        os.path.dirname(os.path.abspath(args.trace_out))
        if args.trace_out else None
    )
    monitor = attach_monitor(
        metrics_port=args.metrics_port,
        tracer=tracer,
        preemption=preempt,
        watchdog=args.watchdog == "on",
        config=WatchdogConfig(
            escalate_after_polls=(
                5 if args.watchdog_escalate == "preempt"
                and preempt is not None else 0
            ),
        ),
        profile_dir=profile_dir,
        rank=rank,
    )
    registry = monitor.registry
    m_loss_gauge = registry.gauge(
        "train_loss", "Training loss at the last logged step"
    )

    from distributed_neural_network_tpu.train.guard import (
        check_cursor,
        resume_cursor,
    )

    from distributed_neural_network_tpu.train import elastic as EL

    def current_mesh_meta():
        """Save-time topology of the CURRENT mesh (re-read after an
        in-process shrink: mesh/specs/accum are rebound locals)."""
        return EL.lm_mesh_meta(
            mesh, specs, args.optimizer,
            batch=args.batch_size, accum_steps=args.accum_steps,
            pp_interleave=args.pp_interleave,
        )

    def ckpt_meta(i: int, loss_val):
        """Checkpoint meta incl. the versioned exact-resume cursor: every
        batch/PRNG stream here is a pure function of (seed, step), so the
        cursor pins the continuation's data order bit-exactly. mesh_meta
        records the save-time topology so a restore into a different
        mesh/optimizer is detected and - with --elastic - resharded
        (parallel/reshard.py) instead of crashing inside pjit."""
        return {"mesh": mesh_desc, "optimizer": args.optimizer,
                "mom_format": MOM_FORMAT, "loss": loss_val,
                "pp_interleave": args.pp_interleave,
                "mesh_meta": current_mesh_meta(),
                **resume_cursor(step=i, seed=args.seed)}

    ck = None
    step0 = 0
    if args.checkpoint_dir:
        from distributed_neural_network_tpu.utils.checkpoint import (
            TreeCheckpointer,
        )

        ck = TreeCheckpointer(args.checkpoint_dir, registry=registry)
        if not args.resume and ck.latest_step() is not None:
            raise SystemExit(
                f"--checkpoint-dir {args.checkpoint_dir} already contains "
                f"checkpoints (latest step {ck.latest_step()}); pass "
                "--resume to continue that run or use a fresh directory "
                "(saves at existing step numbers would be silently skipped)"
            )
        if args.resume and args.elastic:
            restored = EL.elastic_restore(
                ck, cfg=cfg, mesh=mesh, specs=specs,
                optimizer=args.optimizer,
                param_shardings=param_shardings,
                mom_shardings=mom_shardings,
                current_meta=current_mesh_meta(),
                tracer=tracer, registry=registry,
            )
            if restored is None:
                print(
                    f"(WARNING: --resume found no checkpoint in "
                    f"{args.checkpoint_dir}; starting from scratch)"
                )
            else:
                state, meta, last, resharded = restored
                try:
                    check_cursor(meta, seed=args.seed)
                except ValueError as e:
                    raise SystemExit(str(e))
                params, mom = state["params"], state["mom"]
                step0 = last + 1
                if resharded and not pipe:
                    new_accum = EL.rescaled_accum_steps(
                        meta.get("mesh_meta") or {}, batch=args.batch_size,
                        new_dp=args.dp, accum_steps=args.accum_steps,
                    )
                    if new_accum != args.accum_steps:
                        print(
                            f"(elastic: accum-steps {args.accum_steps} -> "
                            f"{new_accum} keeps the global batch "
                            f"{args.batch_size} - and with it the data "
                            "cursor - exact across the dp change)"
                        )
                        args.accum_steps = new_accum
                        step = build_step()
                print(f"(Resumed from step {last}; continuing at {step0})")
        elif args.resume:
            restored = ck.restore_latest(
                {"params": params, "mom": mom},
                {"params": param_shardings, "mom": mom_shardings},
            )
            if restored is None:
                print(
                    f"(WARNING: --resume found no checkpoint in "
                    f"{args.checkpoint_dir}; starting from scratch)"
                )
            if restored is not None:
                state, meta, last = restored
                # mom_format guards against checkpoints from before the
                # ZeRO momentum layout change (flat buffer -> per-leaf
                # tree): the mesh/optimizer checks pass on those but
                # restore then dies on an opaque tree-structure mismatch,
                # so reject with a clear message instead. Only the 'zero'
                # layout ever changed - sgd checkpoints without the key
                # (written before the key existed) restore fine and are
                # accepted.
                checks = [("mesh", mesh_desc), ("optimizer", args.optimizer)]
                if args.optimizer.startswith("zero"):
                    checks.append(("mom_format", MOM_FORMAT))
                if pipe:
                    # interleave permutes the layer axis on device
                    # (interleave_layer_order), so a checkpoint written at
                    # a different v holds a different layer order. Old
                    # checkpoints without the key were written at v=1.
                    meta.setdefault("pp_interleave", 1)
                    checks.append(("pp_interleave", args.pp_interleave))
                for key_, want in checks:
                    if meta.get(key_) != want:
                        raise SystemExit(
                            f"checkpoint was written with {key_}="
                            f"{meta.get(key_)!r}, this run has {want!r} - "
                            "momentum/param shards don't map across layouts; "
                            "resume with the original flags, or pass "
                            "--elastic to reshard the checkpoint onto this "
                            "run's layout (parallel/reshard.py)"
                            + (
                                " (or restart training: this checkpoint "
                                "predates the current momentum layout)"
                                if key_ == "mom_format" else ""
                            )
                        )
                try:
                    check_cursor(meta, seed=args.seed)
                except ValueError as e:
                    raise SystemExit(str(e))
                params, mom = state["params"], state["mom"]
                step0 = last + 1
                print(f"(Resumed from step {last}; continuing at {step0})")

    zperm = None
    if not pipe and args.attn == "zigzag" and args.sp > 1:
        # zigzag layout: permute the sequence axis so each device's shard
        # holds one early + one late chunk; next-token loss is a mean over
        # positions, so a consistent permutation of (tokens, targets)
        # leaves it unchanged
        from distributed_neural_network_tpu.parallel.ring import zigzag_order

        zperm = zigzag_order(args.seq_len, args.sp)

    stream = None
    if args.data_path:
        from distributed_neural_network_tpu.data.tokens import (
            load_token_stream,
            sample_batch,
        )

        stream = load_token_stream(args.data_path, vocab_size=args.vocab)
        print(f"(token stream: {len(stream.tokens):,} tokens "
              f"[{stream.source}], {stream.n_eval:,} held out)")

        def batch_at(i, split="train"):
            tok, tgt = sample_batch(
                stream, batch=args.batch_size, seq_len=args.seq_len,
                step=i, seed=args.seed, split=split,
            )
            tok, tgt = jnp.asarray(tok), jnp.asarray(tgt)
            if zperm is not None:
                tok, tgt = tok[:, zperm], tgt[:, zperm]
            return place_batch(tok, tgt)

        tokens, targets = batch_at(0)
    else:
        tokens, targets = lmtrain.make_copy_task(
            jax.random.key(args.seed + 1),
            batch=args.batch_size, seq_len=args.seq_len, vocab=args.vocab,
        )
        if zperm is not None:
            tokens, targets = tokens[:, zperm], targets[:, zperm]
        tokens, targets = place_batch(tokens, targets)

    eval_fn = None
    if args.eval_every and pipe:
        # held-out eval through the same microbatch schedule, no grad
        # (r3 ADVICE: --eval-every used to be silently ignored under --pp)
        eval_fn = ppl.make_pp_eval_fn(
            cfg, mesh, n_microbatches=args.microbatches,
            loss_chunks=args.loss_chunks, interleave=args.pp_interleave,
        )
    elif args.eval_every:
        from jax.sharding import PartitionSpec as _P

        from distributed_neural_network_tpu import compat as _compat

        tp_ax = lmtrain.TP_AXIS if args.tp > 1 else None
        sp_ax = lmtrain.SEQ_AXIS if args.sp > 1 else None
        sync = tuple(a for a in (lmtrain.DATA_AXIS, lmtrain.SEQ_AXIS)
                     if a in mesh.axis_names)
        eval_fn = jax.jit(
            _compat.shard_map(
                lambda p, tok, tgt: lmtrain.lm_loss(
                    p, tok, tgt, cfg, seq_axis=sp_ax, tp_axis=tp_ax,
                    ep_axis=lmtrain._ep_axis(cfg, mesh),
                    attn_impl=args.attn, axes=sync,
                ),
                mesh=mesh,
                in_specs=(specs, _P(lmtrain.DATA_AXIS, lmtrain.SEQ_AXIS),
                          _P(lmtrain.DATA_AXIS, lmtrain.SEQ_AXIS)),
                out_specs=_P(),
                # the own flash kernels are vma-typed (r4); only the
                # library kernel (lib impl, single-device-gated) needs
                # the checker off
                check_vma=not (
                    args.attn == "flash"
                    and os.environ.get("DNN_TPU_FLASH_IMPL") == "lib"
                ),
            )
        )
    print(
        f"(LM {tfm.param_count(params):,} params, mesh {mesh_desc}, "
        f"attn={args.attn if args.sp > 1 or args.attn == 'flash' else 'full'}, "
        + (f"precision={args.precision}, " if args.precision != "bf16" else "")
        + f"experts={args.experts or 'dense'}, optimizer={args.optimizer})"
    )

    first_loss = None
    t_compile = time.perf_counter()
    t0 = None
    from distributed_neural_network_tpu.utils import metrics as M

    run = M.init_run(jsonl_path=args.metrics_jsonl) if args.metrics_jsonl \
        else M.MetricsRun([])
    run["parameters"] = {
        "mesh": mesh_desc, "optimizer": args.optimizer, "lr": args.lr,
        "lr_schedule": args.lr_schedule, "batch_size": args.batch_size,
        "seq_len": args.seq_len, "d_model": args.d_model,
        "n_layers": args.n_layers, "dtype": args.dtype,
    }
    # step-level telemetry (utils/tracing.py; docs/OBSERVABILITY.md).
    # The traced wrapper fences each step (hard_block on the loss), so the
    # tokens/s this run reports includes one device->host fetch per step -
    # opt-in observability, not the measurement path (train/measure.py).
    # The tracer itself was created up front with the monitor.
    stats = None
    if args.trace_out or args.step_stats:
        from distributed_neural_network_tpu.train.measure import (
            model_flops_per_token as _mfpt,
            peak_flops as _peakf,
        )

        step_extra = (
            (jnp.int32(step0),)
            if args.lr_schedule != "constant" or fault_plan is not None
            else ()
        )
        hw_flops = TRC.compiled_flops(
            step, params, mom, tokens, targets, *step_extra
        )
        # shardlint static cross-check: the analyzer's logical collective
        # payload for THIS compiled step, reported next to the runtime
        # ring estimate below (tools/trace_summary.py --lint compares a
        # recorded trace against the checked-in manifests the same way)
        static_comm = None
        try:
            from distributed_neural_network_tpu.analysis.trace import (
                collect_trace,
            )

            static_comm = collect_trace(
                jax.make_jaxpr(step)(params, mom, tokens, targets,
                                     *step_extra)
            ).total_collective_bytes()
        except Exception:
            pass
        # gradient sync rides the data (and seq) axes; tensor-sharded
        # leaves keep local grads - this over-counts those, an estimate
        n_sync = mesh.shape.get("data", 1) * mesh.shape.get("seq", 1)
        overlap = args.grad_sync == "overlap" and args.accum_steps > 1
        bucket_bytes_list = None
        if overlap:
            # the same deterministic plan the compiled step uses (leaf
            # buckets grouped by PartitionSpec) - per-bucket bytes go to
            # the StepStats summary and, below, in-band into the trace
            from distributed_neural_network_tpu.parallel.collectives import (
                plan_buckets,
            )

            layout = plan_buckets(
                params, bucket_bytes=int(args.bucket_mb * 2**20),
                group_keys=[
                    str(s) for s in jax.tree.leaves(
                        specs, is_leaf=lambda s: isinstance(s, P)
                    )
                ],
            )
            bucket_bytes_list = [int(b) for b in layout.bucket_bytes()]
            comm_bytes = TRC.overlapped_collective_bytes(
                bucket_bytes_list, n_sync, args.accum_steps
            )
        else:
            comm_bytes = TRC.collective_bytes_per_sync(params, n_sync)
        stats = TRC.StepStats(
            item_label="tokens",
            sink=run if args.step_stats else None,
            registry=registry,
            n_devices=mesh.devices.size,
            comm_bytes_per_step=comm_bytes,
            static_comm_bytes_per_step=static_comm,
            grad_sync=args.grad_sync,
            comm_bucket_bytes=bucket_bytes_list,
            compilation_cache_dir=args.compilation_cache_dir,
            flops_per_step=(
                hw_flops if hw_flops is not None
                else _mfpt(cfg, args.seq_len) * args.batch_size * args.seq_len
            ),
            flops_source="cost_analysis" if hw_flops is not None else "analytic",
            peak_flops_per_device=_peakf(
                jax.devices()[0].device_kind, args.dtype
            ),
        )
        if overlap and tracer.enabled:
            TRC.record_bucket_plan(
                tracer, bucket_bytes_list, schedule="overlap",
                op=("reduce_scatter" if args.optimizer.startswith("zero")
                    else "psum"),
                axis_size=n_sync, accum_steps=args.accum_steps,
            )

    # telemetered = the traced wrapper (and with it the goodput ledger's
    # per-step feed) is active; the bare fast path attributes coarsely at
    # run end instead (fencing every step just to time it would change
    # the run being accounted)
    telemetered = (
        stats is not None or monitor.server is not None
        or monitor.heartbeat is not None
    )

    def wrap_step(fn, first_step):
        """Span tracing + StepStats + live registry publishing around a
        compiled step (identity when all telemetry is off); re-applied
        after a guard LR-backoff rebuild. The recompile detector is
        re-baselined on the (new) fn so deliberate rebuilds never count
        as cache misses."""
        if monitor.recompiles is not None:
            monitor.recompiles.swap(fn)
        if not telemetered:
            return fn
        return lmtrain.make_traced_step(
            fn, tracer=tracer, step_stats=stats,
            items_per_step=args.batch_size * args.seq_len,
            fence=True, first_step=first_step,
            registry=registry, recompiles=monitor.recompiles,
        )

    step = wrap_step(step, step0)

    # self-healing layer (train/guard.py; docs/ROBUSTNESS.md)
    monkey = None
    stall_at = tuple(args.chaos_stall_step or ())
    if stall_at and args.chaos_stall_rank is not None \
            and (rank if rank is not None else 0) != args.chaos_stall_rank:
        stall_at = ()  # this rank is not the designated straggler
    if (args.chaos_spike_step or stall_at
            or args.chaos_sigterm_after is not None
            or args.chaos_shrink_at_step is not None):
        from distributed_neural_network_tpu.parallel.fault import ChaosMonkey

        monkey = ChaosMonkey(
            spike_at=tuple(args.chaos_spike_step or ()),
            sigterm_after=args.chaos_sigterm_after,
            stall_at=stall_at,
            stall_s=args.chaos_stall_seconds,
            shrink_at=args.chaos_shrink_at_step,
            preempt=preempt,
            tracer=tracer,
        )
    # training-dynamics observatory (train/dynamics.py): the sink decodes
    # the step's extra telemetry bundle one step behind (same cadence as
    # the guard's HealthPipe) and doubles as the guard's non-finite
    # provenance source, so it is built BEFORE the guard
    dsink = None
    if args.dynamics:
        from distributed_neural_network_tpu.parallel.rules import (
            named_leaves,
        )
        from distributed_neural_network_tpu.train.dynamics import (
            DynamicsSink,
        )

        want_gns = args.grad_sync == "end" and args.accum_steps >= 2
        dsink = DynamicsSink(
            [p_ for p_, _ in named_leaves(params)],
            jsonl_path=args.dynamics_jsonl,
            registry=registry, tracer=tracer,
            # GNS batch sizes in tokens: per-microbatch vs accumulated
            b_small=(args.batch_size * args.seq_len / args.accum_steps
                     if want_gns else None),
            b_big=(args.batch_size * args.seq_len if want_gns else None),
        )
    guard = hpipe = None
    if guard_on:
        guard = G.TrainingGuard(
            G.GuardConfig(
                policy=args.guard,
                spike_zscore=args.guard_spike_zscore,
                snapshot_every=args.snapshot_every,
                max_retries=args.max_retries,
            ),
            tracer=tracer, step_stats=stats, registry=registry,
            provenance=dsink.bad_layer if dsink is not None else None,
        )
        hpipe = G.HealthPipe(
            guard, perturb=monkey.perturb if monkey is not None else None
        )

    ema = ema_fn = None
    if args.ema_decay:
        from distributed_neural_network_tpu.ops.schedule import (
            make_ema_update,
        )

        ema_fn = make_ema_update(args.ema_decay)
        ema = jax.tree.map(jnp.array, params)
    scheduled = args.lr_schedule != "constant"
    takes_step = scheduled or fault_plan is not None
    last_eval = None
    eval_s = 0.0
    preempted = False
    timed_steps = 0
    end_step = (
        args.stop_at_step if args.stop_at_step is not None
        else step0 + args.steps
    )
    if end_step <= step0:
        # a supervised relaunch after the target step was already reached
        # (e.g. the group shrank on the very last checkpoint): nothing to
        # train, exit cleanly so the supervisor records completion
        print(f"(stop-at-step {end_step} already reached - resumed at "
              f"step {step0}; nothing to do)")
        if preempt is not None:
            preempt.uninstall()
        if ck is not None:
            ck.close()
        run.stop()
        G_LEDGER.finalize(metrics={"last_step": step0 - 1,
                                   "nothing_to_do": True})
        monitor.close()
        return 0
    i = last_step = step0

    def handle_verdict(v) -> bool:
        """Apply a guard verdict; True = rolled back (the loop restarts at
        the snapshot step with the rebuilt backed-off step fn)."""
        nonlocal params, mom, step, i
        if v is None or v.action in ("ok", "warn", "skip"):
            return False
        # at_step sizes the ledger's rollback_recompute window (the
        # replayed steps are lost progress being re-earned, not goodput);
        # raises GuardAbort when the retry budget is exhausted
        rb = guard.rollback(at_step=i)
        if rb is None and ck is not None:
            # no in-memory snapshot yet: fall back to the newest on-disk
            # checkpoint (same exact-resume contract)
            restored = ck.restore_latest(
                {"params": params, "mom": mom},
                {"params": param_shardings, "mom": mom_shardings},
            )
            if restored is not None:
                state, _meta, last = restored
                rb = (last + 1, state)
                if i > last + 1:
                    G_LEDGER.mark_recompute(i - (last + 1))
                print(f"(guard: no snapshot yet; restored the on-disk "
                      f"checkpoint at step {last})")
        if rb is None:
            raise G.GuardAbort(
                "guard rollback requested before any snapshot or on-disk "
                "checkpoint exists - lower the LR, enable --checkpoint-dir,"
                " or start with --guard warn to observe first"
            )
        snap_step, state = rb
        params = jax.device_put(state["params"], param_shardings)
        mom = jax.device_put(state["mom"], mom_shardings)
        step = wrap_step(build_step(guard.lr_scale), snap_step)
        print(f"(guard: resuming from step {snap_step} at "
              f"lr_scale={guard.lr_scale:g} [one recompile])")
        hpipe.clear()
        if dsink is not None:
            dsink.clear()  # the stashed step's update never retired
        i = snap_step
        return True

    def do_elastic_shrink(new_dp: int, at_step: int) -> None:
        """Answer a SHRINK preemption in process: the emergency checkpoint
        is already on disk; rebuild the mesh from the surviving device
        prefix, reshard the checkpoint onto it (the same elastic_restore
        path a fresh process would take - ZeRO shards re-pad for the new
        dp), re-slice gradient accumulation so the global batch and data
        cursor stay exact, and rebuild+rewrap the compiled step."""
        nonlocal mesh, specs, param_shardings, mom_shardings, mesh_desc
        nonlocal params, mom, step, ema
        from distributed_neural_network_tpu.parallel.reshard import (
            place_tree,
            rescale_accum,
        )

        old_dp = mesh.shape.get("data", 1)
        mesh = lmtrain.create_lm_mesh(new_dp, args.sp, args.tp)
        specs, param_shardings, mom_shardings = lmtrain.make_lm_shardings(
            cfg, mesh, args.optimizer, rules=shard_rules
        )
        args.accum_steps = rescale_accum(
            args.batch_size, old_dp, new_dp, args.accum_steps
        )
        args.dp = new_dp
        mesh_desc = "x".join(
            f"{k}{v}" for k, v in mesh.shape.items() if v > 1
        ) or "single"
        restored = EL.elastic_restore(
            ck, cfg=cfg, mesh=mesh, specs=specs, optimizer=args.optimizer,
            param_shardings=param_shardings, mom_shardings=mom_shardings,
            current_meta=current_mesh_meta(), tracer=tracer,
            registry=registry,
        )
        state, _meta, _last, _resharded = restored
        params, mom = state["params"], state["mom"]
        step = wrap_step(
            build_step(guard.lr_scale if guard is not None else 1.0),
            at_step + 1,
        )
        if ema is not None:
            ema = place_tree(ema, param_shardings)
        if guard is not None:
            # rolling snapshots hold the pre-shrink layout; a later
            # rollback must not restore them - the next cadence retakes
            guard.drop_snapshot()
        if hpipe is not None:
            hpipe.clear()
        if dsink is not None:
            dsink.clear()
            # the shrink re-sliced accumulation: the GNS per-microbatch
            # token count follows (the rebuilt step stops emitting
            # msq_small entirely if accum collapsed to 1)
            if dsink.b_small is not None and args.accum_steps >= 2:
                dsink.b_small = (
                    args.batch_size * args.seq_len / args.accum_steps
                )
        print(
            f"(elastic: continuing at step {at_step + 1} on mesh "
            f"{mesh_desc}, accum_steps={args.accum_steps})"
        )

    # the dynamics bundle rides LAST in the step output: after the health
    # bundle when the guard is on (train/lm.py make_lm_train_step)
    dyn_idx = 4 if guard_on else 3
    while i < end_step:
        if guard is not None and (i - step0) % args.snapshot_every == 0:
            # settle the in-flight observation BEFORE snapshotting, so the
            # rolling snapshot only ever captures guard-verified state
            # (dynamics first: the guard's provenance lookup for the
            # settled step reads the sink's decoded row)
            if dsink is not None:
                dsink.flush()
            if handle_verdict(hpipe.flush()):
                continue
            guard.maybe_snapshot(
                i, {"params": params, "mom": mom}, first_step=step0
            )
        if stream is not None:
            # refresh at EVERY step (including step0): on resume the
            # pre-loop batch is batch_at(0), not batch_at(step0), and a
            # continuous run must see the same stream as a fresh one.
            # Host-side sampling blocks the dispatch - data_wait badput
            with G_LEDGER.interval("data_wait"):
                tokens, targets = batch_at(i)
        if takes_step:
            out = step(params, mom, tokens, targets, jnp.int32(i))
        else:
            out = step(params, mom, tokens, targets)
        params, mom, loss = out[0], out[1], out[2]
        if dsink is not None:
            # BEFORE the health pipe: both are one-step lagged, so when
            # the guard judges step i-1 below, the sink must already have
            # decoded i-1's bundle for the bad_layer provenance lookup
            dsink.push(i, out[dyn_idx])
        if hpipe is not None and handle_verdict(hpipe.push(i, out[3])):
            continue
        if ema_fn is not None:
            ema = ema_fn(ema, params)
        if eval_fn is not None and (i + 1) % args.eval_every == 0:
            import numpy as _np

            t_ev = time.perf_counter()
            eval_params = ema if ema is not None else params
            ev = float(_np.mean([
                float(eval_fn(eval_params, *batch_at(j, "eval")))
                for j in range(args.eval_batches)
            ]))
            # excluded from the throughput window: only training tokens
            # are counted, so eval wall time must not deflate tokens/s.
            # Evals during the warmup/compile step (t0 unset) are outside
            # the window entirely - counting them would inflate tokens/s
            if t0 is not None:
                eval_s += time.perf_counter() - t_ev
            last_eval = {"step": i, "eval_loss": round(ev, 4),
                         "ppl": round(float(_np.exp(min(ev, 30.0))), 2)}
            print(f"step {i:>5}  eval_loss {ev:.4f}  "
                  f"ppl {last_eval['ppl']:.2f}")
            run.append(M.VAL_LOSS, ev)
        if i == step0 and first_loss is None:
            jax.block_until_ready(loss)
            first_loss = float(loss)
            print(f"(first step incl. compile: "
                  f"{time.perf_counter() - t_compile:.1f}s)")
            t0 = time.perf_counter()
        elif t0 is not None:
            timed_steps += 1
        if (i - step0) % args.log_every == 0 or i == end_step - 1:
            print(f"step {i:>5}  loss {float(loss):.4f}")
            run.append(M.TRAIN_LOSS, float(loss))
            m_loss_gauge.set(float(loss))
        if ck is not None and (i + 1) % args.checkpoint_every == 0:
            ck.save(i, {"params": params, "mom": mom},
                    ckpt_meta(i, float(loss)))
        last_step = i
        if monkey is not None:
            monkey.after_step(i)
        if preempt is not None and preempt.requested:
            if ck is not None:
                ck.save(i, {"params": params, "mom": mom},
                        ckpt_meta(i, float(loss)))
            if (preempt.signame == "SHRINK" and ck is not None
                    and args.chaos_shrink_to is not None):
                # elastic path: the emergency checkpoint above is the
                # hand-off; reshard it onto the shrunken mesh and keep
                # training instead of dying with the lost devices
                print(f"(emergency checkpoint at step {i}; SHRINK "
                      "preemption -> resharding onto the surviving "
                      "devices)")
                do_elastic_shrink(args.chaos_shrink_to, i)
                preempt.requested = False
                preempt.signame = None
                i += 1
                continue
            preempted = True
            if ck is not None:
                print(f"(emergency checkpoint at step {i}; resume with "
                      "--resume to continue bit-exactly)")
            else:
                print(f"({preempt.signame}: stopping after step {i}; no "
                      "--checkpoint-dir, progress is lost)")
            break
        i += 1
    from distributed_neural_network_tpu.utils.timers import hard_block

    hard_block(loss)  # value-fetch fence; block_until_ready no-ops on axon
    if not telemetered and t0 is not None:
        # coarse goodput attribution for the bare fast path: the first
        # dispatch (incl. XLA compile) and the post-compile window, as a
        # low-priority FILL so checkpoint saves recorded inside it keep
        # their own bucket (utils/goodput.py fill_ending_now)
        now_l, pc = G_LEDGER.now(), time.perf_counter()
        G_LEDGER.add("compile", now_l - (pc - t_compile),
                     now_l - (pc - t0))
        G_LEDGER.fill_ending_now(
            "steady_step", max(pc - t0 - eval_s, 0.0)
        )
        G_LEDGER.note_steps(
            timed_steps,
            tokens=float(args.batch_size * args.seq_len * timed_steps),
        )
    if preempt is not None:
        preempt.uninstall()
    if dsink is not None:
        # settle before the health pipe's final flush (provenance for the
        # last judged step), then close the JSONL stream
        dsink.flush()
    if hpipe is not None:
        # settle the last step's observation (counters/trace completeness;
        # a final-step rollback has nothing left to re-run, and the abort
        # policy still raises from here)
        hpipe.flush()
    if dsink is not None:
        dsink.close()
    if ck is not None:
        if not preempted:
            ck.save(last_step, {"params": params, "mom": mom},
                    ckpt_meta(last_step, float(loss)))
        ck.close()
    from distributed_neural_network_tpu.train.measure import (
        model_flops_per_token,
        peak_flops,
    )

    # timed_steps counts post-compile steps actually executed (guard
    # replays included, preempted tails excluded), so tokens/s stays
    # honest under rollbacks and early exits
    dt = time.perf_counter() - t0 - eval_s if timed_steps else 0.0
    tok_s = args.batch_size * args.seq_len * timed_steps / dt if dt else 0.0
    flops_tok = model_flops_per_token(cfg, args.seq_len)
    model_flops_s = flops_tok * tok_s
    n_dev = mesh.devices.size
    peak = peak_flops(jax.devices()[0].device_kind, args.dtype)
    mfu = model_flops_s / (peak * n_dev) * 100.0 if peak else None
    if mfu is not None:
        peak_label = (
            "bf16" if args.dtype == "bfloat16" else "f32 (0.5x bf16 MXU)"
        )
        print(
            f"MFU {mfu:.1f}% = {model_flops_s / 1e12:.1f} model TFLOP/s / "
            f"({peak / 1e12:.0f} peak {peak_label} TFLOP/s x {n_dev} dev); "
            f"FLOPs/token = 3*(L*(8d^2 + 4sd + 4d*ff) + 2d*V) "
            f"= {flops_tok / 1e6:.1f}M"
        )
    if args.generate > 0:
        if pipe:
            print("(--generate skipped: decode needs the non-pipeline "
                  "param layout; rerun without --pp)")
        else:
            import numpy as np

            # decode on replicated single-device params (gather the tree);
            # EMA weights when tracked - the eval-side parameters
            host_params = jax.tree.map(
                lambda x: jax.device_put(np.asarray(x), jax.devices()[0]),
                ema if ema is not None else params,
            )
            # fresh unpermuted prompts (zigzag feeds permuted tokens)
            ptoks, _ = lmtrain.make_copy_task(
                jax.random.key(args.seed + 1),
                batch=args.batch_size, seq_len=args.seq_len, vocab=args.vocab,
            )
            half = args.seq_len // 2
            prompt = ptoks[:2, : half + 1]
            out = tfm.generate(
                host_params, prompt, cfg, max_new_tokens=args.generate,
                temperature=args.gen_temperature, top_k=args.gen_top_k,
                top_p=args.gen_top_p,
                key=(jax.random.key(args.seed + 2)
                     if args.gen_temperature > 0 else None),
            )
            for i, row in enumerate(np.asarray(out)):
                cut = half + 1
                print(f"gen[{i}] prompt={row[:cut].tolist()} "
                      f"completion={row[cut:].tolist()}")

    # goodput accounting close-out: finalize ASSERTS conservation (the
    # taxonomy buckets + goodput partition total wall-clock), writes the
    # run record through when armed, and updates the registry export
    goodput_rec = G_LEDGER.finalize(metrics={
        "final_loss": float(loss), "first_loss": first_loss,
        "last_step": last_step, "preempted": preempted,
        "tokens_per_s": round(tok_s),
        "mfu_pct": round(mfu, 2) if mfu is not None else None,
    })

    if stats is not None:
        stats.capture_memory(tracer)
        if args.step_stats:
            print(stats.report())
    if args.trace_out:
        tracer.export(args.trace_out, step_stats=stats,
                      goodput=goodput_rec)
        print(f"(Chrome trace written to {args.trace_out}; open in "
              "Perfetto / chrome://tracing, or summarize with "
              "tools/trace_summary.py)")
    run.stop()
    # pipeline bubble: (P-1)/(v*M+P-1) of tick-time processes garbage;
    # raise --microbatches or --pp-interleave to shrink it (the head is
    # not paid per tick)
    bubble = (
        round(
            (args.pp - 1)
            / (args.pp_interleave * args.microbatches + args.pp - 1),
            4,
        )
        if pipe else None
    )
    if guard is not None:
        print("(guard summary: " + json.dumps(guard.summary()) + ")")
    print("GOODPUT " + json.dumps({
        "goodput_ratio": goodput_rec["goodput_ratio"],
        "wall_s": goodput_rec["wall_s"],
        "goodput_s": goodput_rec["goodput_s"],
        "badput_s": {k: v for k, v in goodput_rec["badput_s"].items()
                     if v > 0},
        "steps": goodput_rec["steps"],
        "record": G_LEDGER.path,
    }))
    print("SUMMARY " + json.dumps({
        "mesh": mesh_desc, "steps": args.steps, "start_step": step0,
        "last_step": last_step, "preempted": preempted,
        "guard": args.guard,
        "guard_summary": guard.summary() if guard is not None else None,
        "dtype": args.dtype, "pp_bubble_frac": bubble,
        "grad_sync": args.grad_sync, "accum_steps": args.accum_steps,
        "dynamics": (
            {"rows": dsink.rows_written, "jsonl": args.dynamics_jsonl}
            if dsink is not None else None
        ),
        "data_source": stream.source if stream is not None else "copy-task",
        "eval": last_eval,
        "first_loss": first_loss, "final_loss": float(loss),
        "tokens_per_s": round(tok_s), "wall_s_post_compile": round(dt, 3),
        "model_tflops_per_s": round(model_flops_s / 1e12, 2),
        "mfu_pct": round(mfu, 2) if mfu is not None else None,
    }))
    from distributed_neural_network_tpu.utils.obs import flight_event

    flight_event("run_end", step=last_step, preempted=preempted)
    if monitor.server is not None and args.metrics_linger > 0:
        print(f"(metrics server lingering {args.metrics_linger:g}s for "
              "final scrapes)")
        time.sleep(args.metrics_linger)
    monitor.close()
    if preempted and os.environ.get("DNN_TPU_SUPERVISOR"):
        # tell the supervisor (train/supervisor.py) this is a clean
        # PREEMPTION, not workload completion: the emergency checkpoint
        # is on disk and the group should restart from it. os._exit skips
        # the jax distributed-runtime shutdown barrier - on a preemption
        # the OTHER ranks are usually still mid-step, and waiting for
        # them would hold the exit (and the supervisor's restart) for the
        # barrier's multi-minute timeout.
        from distributed_neural_network_tpu.train.supervisor import (
            PREEMPT_RC,
        )

        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(PREEMPT_RC)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except Exception as e:
        from distributed_neural_network_tpu.train.guard import GuardAbort

        if isinstance(e, GuardAbort):
            # actionable one-liner instead of a traceback: the message
            # already says what happened and what to do next
            raise SystemExit(f"GUARD ABORT: {e}")
        raise
